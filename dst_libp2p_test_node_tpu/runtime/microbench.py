"""Microbenchmark + kernel autotune harness (ISSUE 16).

arXiv:1912.03413's methodology, applied to this repo's registered hot
entrypoints: measure where each compiled program sits on the roofline
BEFORE optimizing it, and pick kernel block sizes from measurement rather
than folklore. Three sections, each emitting strict JSON:

  rooflines      per-EntrypointContract {flops, hbm_bytes,
                 peak_memory_bytes, retraces} from runtime/profiling.py,
                 EXTENDED with a measured min-of-k wall and the derived
                 achieved GFLOP/s, HBM GB/s and arithmetic intensity —
                 the two coordinates that place the program on the
                 roofline plot.
  kernel_sweep   explicit row-block sweep over the Pallas kernels
                 (native/vmem_gather.py, native/score_update.py): every
                 power-of-two block that tiles the rung is timed via the
                 kernels' `block_rows` override, and the winners become a
                 `tuned` block-size table. `--install` writes it to
                 native/tuned.json (see native/tuned.py), which the
                 kernels' block choosers consult before their heuristic.
                 On CPU the sweep runs `interpret=True` — a functional
                 sweep (CI exercises the full path and the artifact
                 schema), not a performance claim; only a TPU run's
                 table is worth installing.
  packed_state_ab
                 the SimParams.packed_state A/B (bf16 per-edge cost
                 tables on the receiver-side fixpoint): one timed publish
                 per setting at the requested rung, plus a lowered-HLO
                 comparison that reports whether the flag changed the
                 compiled program AT ALL (below the row-gather budget on
                 a single device the receiver-side formulation is not
                 dispatched and the flag is dead). The recorded verdict
                 keeps the default off: exact delivery is the model of
                 record and bf16 packing breaks its bit guarantee, so a
                 wall-clock win alone can never flip the default.

CLI: `python -m dst_libp2p_test_node_tpu microbench [--out FILE]
[--install] [--only PREFIX] [--no-retraces] [--no-rooflines]
[--no-sweep] [--no-packed] [--sweep-rows N] [--sweep-cap C]
[--packed-n N] [--reps K]`.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import time

# the sweep's block ceiling mirrors the kernels' own VMEM ceiling
_MAX_BLOCK = 512


def _min_wall(thunk, reps: int) -> float:
    """Min-of-reps wall of an already-warm thunk (the bench's
    contention-robust estimator)."""
    import jax

    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.time()
        jax.block_until_ready(thunk())
        best = min(best, time.time() - t0)
    return best


def registry_rooflines(name_prefix: str | None = None,
                       with_retraces: bool = True, reps: int = 3) -> dict:
    """The profiling.roofline block per contract, extended with a measured
    wall and the derived roofline coordinates. A contract that cannot
    build/run on this backend degrades to an `error` entry (same contract
    as roofline() itself — the harness must keep emitting)."""
    import jax

    from ..analysis.registry import default_contracts
    from .profiling import roofline

    contracts = default_contracts()
    if name_prefix:
        contracts = [c for c in contracts if c.name.startswith(name_prefix)]
    block = roofline(contracts, with_retraces=with_retraces,
                     name_prefix=name_prefix or "")
    for c in contracts:
        entry = block.get(c.name)
        if entry is None or "error" in entry:
            continue
        try:
            thunk = c.build().thunk()
            jax.block_until_ready(thunk())            # warm (compile)
            wall = _min_wall(thunk, reps)
            entry["wall_s"] = round(wall, 6)
            flops = entry.get("flops")
            hbm = entry.get("hbm_bytes")
            if flops and wall > 0:
                entry["gflops_per_s"] = round(flops / wall / 1e9, 3)
            if hbm and wall > 0:
                entry["hbm_gbytes_per_s"] = round(hbm / wall / 1e9, 3)
            if flops and hbm:
                entry["arith_intensity"] = round(flops / hbm, 4)
        except Exception as e:  # noqa: BLE001 — per-entry degradation
            entry["error"] = repr(e)[:200]
    return block


def _candidate_blocks(n_rows: int, interpret: bool) -> list[int]:
    """Every power-of-two row block <= _MAX_BLOCK that tiles n_rows
    exactly; the real kernel additionally needs >= 8 rows to meet the
    (8, 128) f32 tiling floor (interpret mode has no such floor)."""
    out = []
    b = 1
    while b <= _MAX_BLOCK:
        if n_rows % b == 0 and (interpret or b >= 8):
            out.append(b)
        b *= 2
    return out


def sweep_kernels(n_rows: int = 4096, cap: int = 16, reps: int = 5,
                  interpret: bool | None = None) -> dict:
    """Time every candidate row block of both Pallas kernels at one
    (n_rows, cap) rung via their `block_rows` override; the per-kernel
    winner is the tuned table entry."""
    import jax
    import jax.numpy as jnp

    from ..native.score_update import score_update
    from ..native.vmem_gather import vmem_gather
    from ..ops.state import SimParams

    if interpret is None:
        # off-TPU the real kernel cannot compile; the interpreter run is
        # a functional sweep, flagged as such in the artifact
        interpret = jax.default_backend() != "tpu"

    t = jnp.arange(n_rows, dtype=jnp.float32) * 0.5
    src = (jnp.arange(n_rows * cap, dtype=jnp.int32)
           .reshape(n_rows, cap) * 7) % n_rows
    params = SimParams(n=n_rows, capacity=cap, slow_weight=-10.0)
    fmd = (jnp.arange(n_rows * cap, dtype=jnp.float32)
           .reshape(n_rows, cap) % 13) * 0.3
    slow = (jnp.arange(n_rows * cap, dtype=jnp.float32)
            .reshape(n_rows, cap) % 7) * 0.2

    calls = {
        "vmem_gather": lambda b: vmem_gather(
            t, src, interpret=interpret, block_rows=b),
        "score_update": lambda b: score_update(
            fmd, slow, 0.9, 0.8, params, interpret=interpret, block_rows=b),
    }
    out: dict = {"n_rows": n_rows, "cap": cap, "interpret": interpret,
                 "kernels": {}}
    for name, call in calls.items():
        cands: dict = {}
        best_b, best_w = None, float("inf")
        for b in _candidate_blocks(n_rows, interpret):
            try:
                jax.block_until_ready(call(b))        # warm (compile)
                wall = _min_wall(lambda: call(b), reps)  # noqa: B023
            except Exception as e:  # noqa: BLE001 — candidate degrades
                cands[str(b)] = {"error": repr(e)[:120]}
                continue
            cands[str(b)] = round(wall, 6)
            if wall < best_w:
                best_b, best_w = b, wall
        out["kernels"][name] = {
            "candidates": cands,
            "best_block_rows": best_b,
            "best_wall_s": (round(best_w, 6) if best_b is not None
                            else None),
        }
    return out


def packed_state_ab(n: int = 100_000, connect_to: int = 10, reps: int = 3,
                    payload_bytes: int = 15_000, warm_hb: int = 10) -> dict:
    """SimParams.packed_state A/B at one rung: timed publish walls for
    off/on plus a lowered-program comparison, and the recorded verdict.

    The verdict NEVER flips the default from measurement alone: the bench
    timed loop is the exact delivery mode (model of record) and the bf16
    per-edge tables break its bit guarantee by construction (ops/state.py
    packed_state note), so packed can only ever be a bounded-mode knob.
    The A/B records whether it even changes the program at this rung —
    below the row-gather budget on one device the receiver-side
    formulation that reads the flag is not dispatched at all."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ..config.topology import Topology, TopoParams
    from ..ops.disseminate import answer_tables, disseminate, edge_tables
    from ..ops.graph import build_connection_graph
    from ..ops.heartbeat import run_heartbeats
    from ..ops.state import SimParams, graph_arrays, init_state

    topo = Topology.build(TopoParams(
        network_size=n, anchor_stages=5, min_bandwidth=50,
        max_bandwidth=150, min_latency=40, max_latency=130,
        msg_size_bytes=payload_bytes))
    graph = build_connection_graph(n, connect_to, seed=0)
    params = SimParams(n=n, capacity=graph.capacity, serialize_answers=True)
    a = graph_arrays(graph)
    stage = jnp.asarray(topo.stage_of_peer)
    lat = jnp.asarray(topo.latency_ms)
    bw = jnp.asarray(topo.bw_up_mbit)
    lat_edge, _ = edge_tables(stage, lat, a["conns"], a["rev"])
    ans_tables = answer_tables(lat_edge, a["conns"])
    state = init_state(params, seed=0)
    state = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                           params, warm_hb)           # form the mesh

    def _pub(p):
        def go(s):
            res, _ = disseminate(
                s, a["conns"], a["rev"], stage, lat, bw, publisher=4,
                t0_ms=s.t_ms, params=p, payload_bytes=payload_bytes,
                lat_edge=lat_edge, ans_tables=ans_tables)
            return res.delay_ms
        return go

    out: dict = {"n_peers": n, "delivery_mode": "exact"}
    digests = {}
    for key, p in (("off", params),
                   ("on", dataclasses.replace(params, packed_state=True))):
        go = _pub(p)
        digests[key] = hashlib.sha256(
            jax.jit(go).lower(state).as_text().encode()).hexdigest()
        jax.block_until_ready(go(state))              # warm (compile)
        out[f"publish_{key}_s"] = round(_min_wall(lambda: go(state), reps),
                                        6)
    identical = digests["off"] == digests["on"]
    out["program_identical"] = identical
    out["packed_over_unpacked"] = round(
        out["publish_off_s"] / max(out["publish_on_s"], 1e-12), 4)
    out["verdict"] = (
        "keep-default-off: exact mode is the model of record and the bf16 "
        "per-edge tables break its bit guarantee, so packed_state can only "
        "be a bounded-mode knob; "
        + ("the flag is DEAD at this rung (receiver-side formulation not "
           "dispatched below the row-gather budget on one device) — the "
           "walls differ only by host noise"
           if identical else
           "the flag is live at this rung (receiver-side formulation "
           "dispatched); the measured ratio above is the bounded-path "
           "trade, not grounds to flip the exact-mode default"))
    return out


def run(argv=None) -> dict:
    """CLI body (`microbench` subcommand): assemble the strict-JSON
    artifact, optionally install the tuned block table."""
    import jax

    from .summarize import sanitize_nonfinite

    ap = argparse.ArgumentParser(
        prog="microbench",
        description="per-kernel rooflines + Pallas block-size autotune")
    ap.add_argument("--out", default="", help="write the artifact here "
                    "(default: print one JSON line)")
    ap.add_argument("--only", default="", metavar="PREFIX",
                    help="restrict rooflines to contracts with this name "
                    "prefix (the full registry costs minutes of compiles)")
    ap.add_argument("--no-retraces", action="store_true",
                    help="skip the per-contract retrace measurement")
    ap.add_argument("--no-rooflines", action="store_true")
    ap.add_argument("--no-sweep", action="store_true")
    ap.add_argument("--no-packed", action="store_true")
    ap.add_argument("--install", action="store_true",
                    help="write the sweep winners to native/tuned.json "
                    "(DST_TUNED_JSON overrides the path)")
    ap.add_argument("--sweep-rows", type=int, default=4096)
    ap.add_argument("--sweep-cap", type=int, default=16)
    ap.add_argument("--packed-n", type=int, default=100_000)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args(argv)

    out: dict = {"metric": "microbench", "backend": jax.default_backend()}
    if not args.no_rooflines:
        out["rooflines"] = registry_rooflines(
            args.only or None, with_retraces=not args.no_retraces,
            reps=args.reps)
    if not args.no_sweep:
        sweep = sweep_kernels(args.sweep_rows, args.sweep_cap, args.reps)
        out["kernel_sweep"] = sweep
        tuned = {k: {"block_rows": v["best_block_rows"]}
                 for k, v in sweep["kernels"].items()
                 if v.get("best_block_rows") is not None}
        out["tuned"] = tuned
        if args.install and tuned:
            from ..native import score_update as _sk
            from ..native import tuned as _tuned
            from ..native import vmem_gather as _vg

            with open(_tuned.tuned_path(), "w") as fh:
                json.dump(tuned, fh, indent=1, sort_keys=True,
                          allow_nan=False)
                fh.write("\n")
            # drop every cache that baked in the pre-install block choice
            _tuned.invalidate_cache()
            _vg._compiled.cache_clear()
            _sk._compiled.cache_clear()
            out["tuned_installed"] = _tuned.tuned_path()
    if not args.no_packed:
        out["packed_state_ab"] = packed_state_ab(args.packed_n,
                                                 reps=args.reps)
    out = sanitize_nonfinite(out)
    text = json.dumps(out, allow_nan=False)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return out
