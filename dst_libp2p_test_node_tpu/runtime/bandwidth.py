"""Bandwidth-utilization channel: Shadow heartbeat-counter parity.

The reference's third experiment output (besides latency lines and
Prometheus) is Shadow's own per-node traffic counters, aggregated by
shadow/summary_shadowlog.awk:12-66 into total/min/max/avg/stddev rx-tx
bytes and a local/remote x in/out packet + ctrl/data header-byte
breakdown (run.sh:70-74 runs it on every shadowlog).

The TPU engine already accounts every byte on-device (ops/disseminate.py
accumulates bytes_tx/bytes_rx/dup_rx per peer; IHAVE/IWANT counts per
message). This module renders those counters in the exact line shape the
awk script parses — field $9 == "[node]", peer name in $5, and a $10
payload whose ",|;"-split layout matches summary_shadowlog.awk:3-8
(rx=arr[2], tx=arr[3], four 12-field flag blocks from arr[7]) — so the
reference's awk runs UNCHANGED on our output, and a Python summarizer that
reproduces the awk math for in-process use.

Packetization model: data bytes ride TCP segments of MSS=1448 (Shadow's
default 1500 MTU minus IP+TCP headers); every segment pays 66 B of
Ethernet+IP+TCP header. Control messages (IHAVE/IWANT) are small single
packets. All simulated traffic is inter-host, so the localhost blocks are
zero (the awk's Details section prints only the remote blocks,
summary_shadowlog.awk:133-140).
"""

from __future__ import annotations

import io
from dataclasses import dataclass

import numpy as np

MSS_BYTES = 1448
HDR_BYTES = 66          # Ethernet 14 + IPv4 20 + TCP 32 (w/ options)
CTRL_PKT_BYTES = 120    # one IHAVE/IWANT rpc frame

_FLAG_BLOCK = 12        # summary_shadowlog.awk:4
_FG_INDEX = 7           # summary_shadowlog.awk:3


@dataclass
class PeerTraffic:
    """Cumulative per-peer traffic, the engine-side source of truth."""

    rx_bytes: np.ndarray        # (N,) data bytes received
    tx_bytes: np.ndarray        # (N,) data bytes sent
    ctrl_rx: np.ndarray         # (N,) control packets received
    ctrl_tx: np.ndarray         # (N,) control packets sent

    @classmethod
    def from_state(cls, state):
        """Build from a SimState. Control packets are real per-peer counters:
        a peer's ctrl_tx is the IHAVEs + IWANTs it sent, ctrl_rx the ones
        addressed to it (SimState.ihave_tx/iwant_tx/ihave_rx/iwant_rx) — the
        shadowlog's per-node ctrl fields are per-node in the reference too
        (summary_shadowlog.awk:3-8)."""
        rx = np.asarray(state.bytes_rx, dtype=np.float64)
        tx = np.asarray(state.bytes_tx, dtype=np.float64)
        ctrl_tx = (np.asarray(state.ihave_tx, dtype=np.float64)
                   + np.asarray(state.iwant_tx, dtype=np.float64)
                   + np.asarray(state.idontwant_tx, dtype=np.float64))
        ctrl_rx = (np.asarray(state.ihave_rx, dtype=np.float64)
                   + np.asarray(state.iwant_rx, dtype=np.float64)
                   + np.asarray(state.idontwant_rx, dtype=np.float64))
        return cls(rx_bytes=rx, tx_bytes=tx, ctrl_rx=ctrl_rx, ctrl_tx=ctrl_tx)


def _data_pkts(data_bytes: np.ndarray) -> np.ndarray:
    return np.ceil(data_bytes / MSS_BYTES)


def shadowlog_lines(traffic: PeerTraffic, sim_time: str = "00:15:00") -> list[str]:
    """One cumulative '[node]' heartbeat line per peer, field-compatible with
    summary_shadowlog.awk ($5 peer, $9 '[node]', $10 counters)."""
    out = []
    n = traffic.rx_bytes.shape[0]
    for i in range(n):
        rx = traffic.rx_bytes[i]
        tx = traffic.tx_bytes[i]
        crx, ctx = traffic.ctrl_rx[i], traffic.ctrl_tx[i]
        d_in_pkt = _data_pkts(rx)
        d_out_pkt = _data_pkts(tx)
        blocks = []
        blocks.append([0] * _FLAG_BLOCK)  # inbound-localhost
        blocks.append([0] * _FLAG_BLOCK)  # outbound-localhost
        for pkt, byt, ctrl in ((d_in_pkt, rx, crx), (d_out_pkt, tx, ctx)):
            b = [0] * _FLAG_BLOCK
            b[0] = int(pkt + ctrl)                      # pkt
            b[1] = int(byt + ctrl * CTRL_PKT_BYTES)     # bytes
            b[2] = int(ctrl)                            # ctrl_pkt
            b[3] = int(ctrl * HDR_BYTES)                # ctrl_hdr_bytes
            b[6] = int(pkt)                             # data_pkt
            b[7] = int(pkt * HDR_BYTES)                 # data_hdr_bytes
            b[8] = int(byt)                             # data_bytes
            blocks.append(b)
        flags = ",".join(str(v) for b in blocks for v in b)
        rx_tot = int(rx + crx * CTRL_PKT_BYTES)
        tx_tot = int(tx + ctx * CTRL_PKT_BYTES)
        # $10 split on ",|;": arr[1]=tag, arr[2]=rx, arr[3]=tx,
        # arr[4..6] pad, arr[7..54] the four flag blocks
        stats = f"heartbeat;{rx_tot},{tx_tot},0,0,0;{flags}"
        out.append(
            f"{sim_time} [shadow] {sim_time} [INFO] pod-{i} n/a shadow "
            f"heartbeat [node] {stats}"
        )
    return out


@dataclass
class BandwidthSummary:
    """The numbers summary_shadowlog.awk:70-140 prints."""

    network_size: int
    total_rx: float
    total_tx: float
    min_rx: float
    max_rx: float
    avg_rx: float
    std_rx: float
    min_tx: float
    max_tx: float
    avg_tx: float
    std_tx: float
    remote_in_pkt: int
    remote_in_bytes: int
    remote_in_ctrl_pkt: int
    remote_in_ctrl_hdr_bytes: int
    remote_in_data_pkt: int
    remote_in_data_hdr_bytes: int
    remote_in_data_bytes: int
    remote_out_pkt: int
    remote_out_bytes: int
    remote_out_ctrl_pkt: int
    remote_out_ctrl_hdr_bytes: int
    remote_out_data_pkt: int
    remote_out_data_hdr_bytes: int
    remote_out_data_bytes: int


def summarize_bandwidth(traffic: PeerTraffic) -> BandwidthSummary:
    """Reproduce the awk aggregation (population stddev, awk:128-129)."""
    rx = traffic.rx_bytes + traffic.ctrl_rx * CTRL_PKT_BYTES
    tx = traffic.tx_bytes + traffic.ctrl_tx * CTRL_PKT_BYTES
    rx_i = np.floor(rx)
    tx_i = np.floor(tx)
    n = rx.shape[0]
    d_in = _data_pkts(traffic.rx_bytes)
    d_out = _data_pkts(traffic.tx_bytes)
    return BandwidthSummary(
        network_size=n,
        total_rx=float(rx_i.sum()),
        total_tx=float(tx_i.sum()),
        min_rx=float(rx_i.min()),
        max_rx=float(rx_i.max()),
        avg_rx=float(rx_i.mean()),
        std_rx=float(rx_i.std()),
        min_tx=float(tx_i.min()),
        max_tx=float(tx_i.max()),
        avg_tx=float(tx_i.mean()),
        std_tx=float(tx_i.std()),
        remote_in_pkt=int((d_in + traffic.ctrl_rx).sum()),
        remote_in_bytes=int(rx_i.sum()),
        remote_in_ctrl_pkt=int(traffic.ctrl_rx.sum()),
        remote_in_ctrl_hdr_bytes=int(traffic.ctrl_rx.sum() * HDR_BYTES),
        remote_in_data_pkt=int(d_in.sum()),
        remote_in_data_hdr_bytes=int(d_in.sum() * HDR_BYTES),
        remote_in_data_bytes=int(np.floor(traffic.rx_bytes).sum()),
        remote_out_pkt=int((d_out + traffic.ctrl_tx).sum()),
        remote_out_bytes=int(tx_i.sum()),
        remote_out_ctrl_pkt=int(traffic.ctrl_tx.sum()),
        remote_out_ctrl_hdr_bytes=int(traffic.ctrl_tx.sum() * HDR_BYTES),
        remote_out_data_pkt=int(d_out.sum()),
        remote_out_data_hdr_bytes=int(d_out.sum() * HDR_BYTES),
        remote_out_data_bytes=int(np.floor(traffic.tx_bytes).sum()),
    )


def report(s: BandwidthSummary) -> str:
    """Textual report in the awk's print shape (summary_shadowlog.awk:127-140)."""
    f = io.StringIO()
    f.write(
        f"\nTotal Bytes Received :  {_num(s.total_rx)} "
        f"Total Bytes Transferred :  {_num(s.total_tx)}\n"
    )
    f.write(
        "Per Node Pkt Receives : min, max, avg, stddev =  "
        f"{_num(s.min_rx)} {_num(s.max_rx)} {_num(s.avg_rx)} {_num(s.std_rx)}\n"
    )
    f.write(
        "Per Node Pkt Transfers: min, max, avg, stddev =  "
        f"{_num(s.min_tx)} {_num(s.max_tx)} {_num(s.avg_tx)} {_num(s.std_tx)}\n"
    )
    f.write("Details...\n")
    f.write(
        f"Remote IN pkt:  {s.remote_in_pkt} Bytes :  {s.remote_in_bytes} "
        f"ctrlPkt:  {s.remote_in_ctrl_pkt} ctrlHdrBytes:  "
        f"{s.remote_in_ctrl_hdr_bytes} DataPkt:  {s.remote_in_data_pkt} "
        f"DataHdrBytes:  {s.remote_in_data_hdr_bytes} DataBytes "
        f"{s.remote_in_data_bytes}\n"
    )
    f.write(
        f"Remote OUT pkt:  {s.remote_out_pkt} Bytes :  {s.remote_out_bytes} "
        f"ctrlPkt:  {s.remote_out_ctrl_pkt} ctrlHdrBytes:  "
        f"{s.remote_out_ctrl_hdr_bytes} DataPkt:  {s.remote_out_data_pkt} "
        f"DataHdrBytes:  {s.remote_out_data_hdr_bytes} DataBytes "
        f"{s.remote_out_data_bytes}\n"
    )
    return f.getvalue()


def _num(x: float) -> str:
    """awk's default OFMT: integers print bare, floats with %.6g."""
    if float(x) == int(x):
        return str(int(x))
    return f"{x:.6g}"
