from .simulator import Simulator, ExperimentConfig, MessageRecord  # noqa: F401
from .summarize import summarize, summarize_file, report, LatencySummary  # noqa: F401
