"""Publisher controller: the in-experiment message injector.

The reference drives publishing from outside the nodes: Shadow bakes
vacp2p/pod-api-requester into the runner image (shadow/Dockerfile:45-53) and
the generated shadow.yaml starts `traffic_sync.py -s <size> -m <messages>
-d <delay> -n <n> --peer-selection id` on the injector fast-node at t=500 s
(shadow/topogen.py:124-136); under K8s the 10ksim publisher does the same
(README.md:21). Either way the controller POSTs
`{"topic","msgSize","version"}` to the chosen node's :8645 /publish at a
fixed inter-message delay.

This module is that controller for the TPU framework's `serve` mode: pure
stdlib HTTP against any set of node-service URLs. Peer selection mirrors the
reference surface: `id` pins one publisher (run.sh publisher_id, run.sh:34),
`rotation` advances to the next target after every message (run.sh:35,
publisher_rotation)."""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

from ..config.env import HTTP_CONTROL_PORT


@dataclass
class InjectResult:
    ok: int = 0
    failed: int = 0
    replies: list = None

    def __post_init__(self):
        if self.replies is None:
            self.replies = []


def publish_once(
    target: str, msg_size: int, topic: str = "test", version: int = 1,
    timeout_s: float = 10.0,
) -> dict:
    """POST one /publish to `target` (host[:port] or full URL)."""
    if not target.startswith("http"):
        if ":" not in target:
            target = f"{target}:{HTTP_CONTROL_PORT}"
        target = f"http://{target}"
    req = urllib.request.Request(
        f"{target}/publish",
        data=json.dumps(
            {"topic": topic, "msgSize": msg_size, "version": version},
            allow_nan=False,
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def inject(
    targets: list[str],
    msg_size: int,
    messages: int,
    delay_s: float,
    topic: str = "test",
    peer_selection: str = "id",
    publisher_id: int = 0,
    timeout_s: float = 10.0,
    sleep=time.sleep,
) -> InjectResult:
    """Drive `messages` publishes at `delay_s` spacing against `targets`.

    peer_selection: 'id' always hits targets[publisher_id % len];
    'rotation' advances one target per message (traffic_sync --peer-selection
    / run.sh publisher_rotation)."""
    if peer_selection not in ("id", "rotation"):
        raise ValueError(f"unknown peer_selection {peer_selection!r}")
    res = InjectResult()
    idx = publisher_id % len(targets)
    for i in range(messages):
        if i > 0 and delay_s > 0:
            sleep(delay_s)
        try:
            reply = publish_once(
                targets[idx], msg_size, topic=topic, timeout_s=timeout_s)
            res.ok += 1
            res.replies.append(reply)
        except (urllib.error.URLError, OSError, ValueError) as e:
            res.failed += 1
            res.replies.append({"status": "error", "message": str(e)})
        if peer_selection == "rotation":
            idx = (idx + 1) % len(targets)
    return res
