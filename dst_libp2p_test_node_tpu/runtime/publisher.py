"""Publisher controller + the batched device-dispatch engine.

The reference drives publishing from outside the nodes: Shadow bakes
vacp2p/pod-api-requester into the runner image (shadow/Dockerfile:45-53) and
the generated shadow.yaml starts `traffic_sync.py -s <size> -m <messages>
-d <delay> -n <n> --peer-selection id` on the injector fast-node at t=500 s
(shadow/topogen.py:124-136); under K8s the 10ksim publisher does the same
(README.md:21). Either way the controller POSTs
`{"topic","msgSize","version"}` to the chosen node's :8645 /publish at a
fixed inter-message delay.

Two halves live here:

  - the HTTP injector for the `serve` mode (pure stdlib, below): peer
    selection mirrors the reference surface — `id` pins one publisher
    (run.sh publisher_id, run.sh:34), `rotation` advances to the next
    target after every message (run.sh:35, publisher_rotation), and
    `burst` posts back-to-back request groups so the resident service's
    batched dispatcher actually sees multi-request pump rounds.

  - the BATCHED DEVICE DISPATCH engine (ISSUE 14, ARCHITECTURE §16):
    `publish_batch_scan` stacks a pump round's same-shape publish requests
    into seed columns — per-request publisher rows, the chained PRNG and
    warm-offset columns riding in the carried SimState — and executes the
    whole batch as ONE compiled device dispatch (a lax.scan whose body is
    the ordinary disseminate program, padded to a static batch width with
    a per-column active cond). The scan carry IS the sequential publish
    chain — same key splits, same uplink/rx occupancy write-backs, same
    warm-start carry — so the stacked batch is bit-identical to the
    equivalent publish() loop while paying one dispatch instead of B
    (tests/test_batched_dispatch.py pins this bitwise). Simulator and
    MultiTopicSimulator expose it as `publish_batch`; the resident
    service's `dispatch_mode="batched"` rides on top.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

from ..config.env import HTTP_CONTROL_PORT


# ---------------------------------------------------------------------------
# Batched device dispatch (ISSUE 14): one compiled scan over seed columns.
# ---------------------------------------------------------------------------

def _batch_scan_impl(state, conns, rev, stage, lat_ms, bw, rows, active,
                     t0_ms, params, payload_bytes, fragments, with_gossip,
                     loss_stage, loss_mode, lat_edge, loss_edge, ans_tables,
                     valid_edge, with_fanout, topic_blocks):
    import jax
    import jax.numpy as jnp

    from ..ops.disseminate import disseminate

    def publish_one(st, row):
        res, new_st = disseminate(
            st, conns, rev, stage, lat_ms, bw,
            publisher=row, t0_ms=t0_ms, params=params,
            payload_bytes=payload_bytes, fragments=fragments,
            with_gossip=with_gossip, mesh=None,
            loss_stage=loss_stage, loss_mode=loss_mode,
            lat_edge=lat_edge, loss_edge=loss_edge,
            ans_tables=ans_tables, valid_edge=valid_edge,
            with_fanout=with_fanout)
        if topic_blocks > 1:
            # Cross-topic occupancy fold: uplink/rx are per NODE, not per
            # (topic, node) row, so fold the blocks before the next column
            # publishes — exactly what MultiTopicSimulator.publish does
            # between sequential dispatches.
            n = new_st.uplink_free_ms.shape[0] // topic_blocks
            u_node = new_st.uplink_free_ms.reshape(topic_blocks, n).max(axis=0)
            r_node = new_st.rx_free_ms.reshape(topic_blocks, n).max(axis=0)
            new_st = new_st.replace(
                uplink_free_ms=jnp.tile(u_node, topic_blocks),
                rx_free_ms=jnp.tile(r_node, topic_blocks))
        ys = {
            "delay_ms": res.delay_ms,
            "received": res.received,
            "sends": res.sends,
            "copies_rx": res.copies_rx,
            "ihave_sent": res.ihave_sent,
            "iwant_sent": res.iwant_sent,
            "answer_wait_max_ms": jnp.asarray(res.answer_wait_max_ms),
            "converged": jnp.asarray(res.converged),
        }
        return new_st, ys

    def body(st, x):
        row, live = x

        def on(st):
            return publish_one(st, row)

        def off(st):
            # Padding column: state passes through untouched (no key split,
            # no occupancy write-back) and the ys slot is all-zero.
            shapes = jax.eval_shape(lambda s: publish_one(s, row)[1], st)
            return st, jax.tree_util.tree_map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype), shapes)

        return jax.lax.cond(live, on, off, st)

    new_state, ys = jax.lax.scan(body, state, (rows, active))
    return ys, new_state


_batch_scan_jit = None


def publish_batch_scan(state, conns, rev, stage, lat_ms, bw, rows, active,
                       t0_ms, params, payload_bytes, fragments, with_gossip,
                       loss_stage, loss_mode, lat_edge, loss_edge, ans_tables,
                       valid_edge, with_fanout, topic_blocks=1):
    """Execute a padded column batch of publishes as ONE device dispatch.

    `rows` is the (B,) int32 publisher-row column (for multi-topic sims the
    row is topic_index * n + publisher), `active` the (B,) bool padding mask;
    both are traced so every batch width up to the pad length shares one
    compiled program. The scan carry is the SimState, which makes the batch
    bit-identical to publishing the active columns sequentially: each column
    sees the previous column's key split, warm-offset advance, and uplink/rx
    occupancy exactly as publish() would. Returns (ys, new_state) where each
    ys leaf is stacked along the batch axis. Callers strip repair-inert
    fields first (runtime/simulator.py does).
    """
    global _batch_scan_jit
    if _batch_scan_jit is None:
        import jax
        _batch_scan_jit = jax.jit(
            _batch_scan_impl,
            static_argnames=("params", "payload_bytes", "fragments",
                            "with_gossip", "loss_mode", "with_fanout",
                            "topic_blocks"))
    return _batch_scan_jit(
        state, conns, rev, stage, lat_ms, bw, rows, active, t0_ms, params,
        payload_bytes, fragments, with_gossip, loss_stage, loss_mode,
        lat_edge, loss_edge, ans_tables, valid_edge, with_fanout,
        topic_blocks)


@dataclass
class InjectResult:
    ok: int = 0
    failed: int = 0
    replies: list = None

    def __post_init__(self):
        if self.replies is None:
            self.replies = []


def publish_once(
    target: str, msg_size: int, topic: str = "test", version: int = 1,
    timeout_s: float = 10.0,
) -> dict:
    """POST one /publish to `target` (host[:port] or full URL)."""
    if not target.startswith("http"):
        if ":" not in target:
            target = f"{target}:{HTTP_CONTROL_PORT}"
        target = f"http://{target}"
    req = urllib.request.Request(
        f"{target}/publish",
        data=json.dumps(
            {"topic": topic, "msgSize": msg_size, "version": version},
            allow_nan=False,
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def inject(
    targets: list[str],
    msg_size: int,
    messages: int,
    delay_s: float,
    topic: str = "test",
    peer_selection: str = "id",
    publisher_id: int = 0,
    timeout_s: float = 10.0,
    burst: int = 1,
    sleep=time.sleep,
) -> InjectResult:
    """Drive `messages` publishes at `delay_s` spacing against `targets`.

    peer_selection: 'id' always hits targets[publisher_id % len];
    'rotation' advances one target per message (traffic_sync --peer-selection
    / run.sh publisher_rotation). `burst` > 1 posts that many messages
    back-to-back before sleeping, so a resident service's pump round sees a
    multi-request fair batch and the batched dispatcher has columns to
    stack."""
    if peer_selection not in ("id", "rotation"):
        raise ValueError(f"unknown peer_selection {peer_selection!r}")
    if burst < 1:
        raise ValueError(f"burst must be >= 1, got {burst}")
    res = InjectResult()
    idx = publisher_id % len(targets)
    for i in range(messages):
        if i > 0 and i % burst == 0 and delay_s > 0:
            sleep(delay_s)
        try:
            reply = publish_once(
                targets[idx], msg_size, topic=topic, timeout_s=timeout_s)
            res.ok += 1
            res.replies.append(reply)
        except (urllib.error.URLError, OSError, ValueError) as e:
            res.failed += 1
            res.replies.append({"status": "error", "message": str(e)})
        if peer_selection == "rotation":
            idx = (idx + 1) % len(targets)
    return res
