"""Host-side profiling harness: XLA cost accounting + retrace counting.

The ROADMAP's exact-mode item needs the microbenchmark-first methodology of
arXiv:1912.03413 — measure where each compiled program sits on the
roofline before optimizing it. This module derives that, per registered
EntrypointContract (analysis/registry.py), from XLA's own compile-time
analyses:

  entrypoint_cost   FLOPs / HBM bytes / peak-memory estimate via
                    jit(...).lower(...).compile().cost_analysis() and
                    .memory_analysis() — version-gated (the analysis
                    surfaces moved across jax releases; absent fields
                    come back None, never a crash)
  count_retraces    a context manager counting jit cache misses (the
                    "Finished tracing + compiling" log events that
                    jax_log_compiles exposes) — the PR 1/PR 3 carry bugs
                    were exactly silent per-iteration retraces
  measure_retraces  calls a contract's representative spec twice with
                    same-aval inputs and returns the SECOND call's
                    retrace count; EntrypointContract.retrace_budget
                    (default 0) turns any excess into a tier-1 failure
                    (tests/test_profiling.py)
  roofline          the strict-JSON per-entrypoint block bench.py merges
                    into BENCH_r*.json detail: {flops, hbm_bytes,
                    peak_memory_bytes, retraces, retrace_budget}
  chrome_trace      flight-recorder curves (ops/telemetry.py) rendered as
                    Chrome-trace/perfetto JSON — one "X" slice per
                    heartbeat with the channel values in args, plus "C"
                    counter tracks for the scalar channels
  profiler_trace    optional jax.profiler capture around a block (the
                    `trace` CLI's --profile-dir and bench's
                    BENCH_PROFILE_DIR use the same mechanism)
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager

import numpy as np

# the pjit cache-miss log lines. jax 0.4.3x logs "Compiling <fn> with
# global shapes and types" (jax._src.interpreters.pxla) once per in-memory
# cache miss; earlier releases logged "Finished tracing + compiling"
# (jax._src.dispatch). A version emits exactly one of the two per miss, so
# matching either counts each miss once. Counting log events instead of
# private cache sizes keeps the counter working through jit-internals
# refactors. (NOT "Finished tracing + transforming": that fires once per
# sub-transform and would overcount a single compile.)
_COMPILE_MARKERS = ("Finished tracing + compiling",
                    "with global shapes and types")


class RetraceCounter:
    """Mutable counter handed out by count_retraces()."""

    def __init__(self):
        self.count = 0
        self.events: list[str] = []


class _CountingHandler(logging.Handler):
    def __init__(self, counter: RetraceCounter):
        super().__init__(level=logging.DEBUG)
        self._counter = counter

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if any(m in msg for m in _COMPILE_MARKERS):
            self._counter.count += 1
            self._counter.events.append(msg[:200])


@contextmanager
def count_retraces():
    """Count jit cache misses (trace+compile events) inside the block.

    Flips jax_log_compiles on for the duration so the events are emitted at
    WARNING, attaches a counting handler to the "jax" logger (every
    jax._src.* module logger propagates into it), and restores both on
    exit. Persistent-compile-cache hits still count — they are in-memory
    cache MISSES (a full retrace happened; only the XLA backend compile was
    skipped), which is exactly what a retrace budget is about."""
    import jax

    counter = RetraceCounter()
    handler = _CountingHandler(counter)
    jlog = logging.getLogger("jax")
    prev = bool(getattr(jax.config, "jax_log_compiles", False))
    jax.config.update("jax_log_compiles", True)
    jlog.addHandler(handler)
    try:
        yield counter
    finally:
        jlog.removeHandler(handler)
        jax.config.update("jax_log_compiles", prev)


def _dynamic(x) -> bool:
    """True when a spec argument is a device-traceable pytree (all leaves
    arrays): those stay jit parameters; everything else (params dataclasses,
    ints, None) is closed over as a static constant."""
    import jax

    leaves = jax.tree_util.tree_leaves(x)
    return bool(leaves) and all(
        isinstance(leaf, (jax.Array, np.ndarray)) for leaf in leaves)


def lower_spec(spec, return_dynamic: bool = False,
               keep_unused: bool = False):
    """Lower a contract's TraceSpec to an XLA program: dynamic (array)
    arguments become jit parameters, static arguments are closure
    constants — the same split every registered entrypoint's own jit
    makes, so the compiled program is the one production calls run.

    With `return_dynamic` also returns the (dyn_args, dyn_kwargs) pytree
    the program was lowered against — the sharding auditor pairs its
    flattened leaves with `compiled.input_shardings` to name each operand
    when attributing replication and per-leaf footprints. That pairing
    needs `keep_unused=True`: by default jit PRUNES parameters the program
    never reads from the compiled executable, which would misalign the
    sharding leaves with the argument pytree."""
    import jax

    arg_dyn = [i for i, a in enumerate(spec.args) if _dynamic(a)]
    kw_dyn = sorted(k for k, v in spec.kwargs.items() if _dynamic(v))
    dyn_args = tuple(spec.args[i] for i in arg_dyn)
    dyn_kwargs = {k: spec.kwargs[k] for k in kw_dyn}

    def call(dyn_pos, dyn_kw):
        full = list(spec.args)
        for i, v in zip(arg_dyn, dyn_pos):
            full[i] = v
        kw = dict(spec.kwargs)
        kw.update(dyn_kw)
        return spec.fn(*full, **kw)

    lowered = jax.jit(call, keep_unused=keep_unused).lower(
        dyn_args, dyn_kwargs)
    if return_dynamic:
        return lowered, (dyn_args, dyn_kwargs)
    return lowered


def entrypoint_cost(contract) -> dict:
    """{flops, hbm_bytes, peak_memory_bytes} for the contract's
    representative program, from XLA's compile-time analyses. Fields the
    backend/version does not expose come back None (strict-JSON null)."""
    compiled = lower_spec(contract.build()).compile()
    out: dict = {"flops": None, "hbm_bytes": None, "peak_memory_bytes": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            flops = ca.get("flops")
            if flops is not None and float(flops) >= 0:
                out["flops"] = float(flops)
            hbm = ca.get("bytes accessed")
            if hbm is not None and float(hbm) >= 0:
                out["hbm_bytes"] = float(hbm)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        peak = (int(ma.argument_size_in_bytes) + int(ma.output_size_in_bytes)
                + int(ma.temp_size_in_bytes) - int(ma.alias_size_in_bytes))
        out["peak_memory_bytes"] = peak
    except Exception:
        pass
    return out


def measure_retraces(contract) -> int:
    """Retrace count of a SECOND same-aval call of the contract's
    representative spec. The first call (fresh spec from contract.build())
    warms every jit cache on the path; the second builds the spec again —
    same shapes, same statics — and must hit every cache, so any count
    above contract.retrace_budget is aval drift at a call boundary."""
    import jax

    warm = contract.build()
    jax.block_until_ready(warm.thunk()())
    spec = contract.build()
    with count_retraces() as counter:
        jax.block_until_ready(spec.thunk()())
    return counter.count


def roofline(contracts=None, with_retraces: bool = True,
             name_prefix: str | None = None) -> dict:
    """The per-entrypoint roofline block: contract name -> {flops,
    hbm_bytes, peak_memory_bytes, retraces, retrace_budget} (strict-JSON
    safe; a contract that cannot lower on this backend reports an `error`
    string instead of crashing the caller — bench must keep emitting).

    `name_prefix` restricts the sweep to contracts whose name starts with
    it (e.g. "disseminate/" for the publish-entrypoint CI artifact — the
    full registry costs minutes of compiles, the publish family seconds).
    Also honored via the BENCH_ROOFLINE_ONLY env var when the caller does
    not pass one."""
    if name_prefix is None:
        name_prefix = os.environ.get("BENCH_ROOFLINE_ONLY") or None
    if contracts is None:
        from ..analysis.registry import default_contracts

        contracts = default_contracts()
    if name_prefix:
        contracts = [c for c in contracts if c.name.startswith(name_prefix)]
    block: dict = {}
    for c in contracts:
        entry: dict = {}
        try:
            entry.update(entrypoint_cost(c))
        except Exception as e:  # noqa: BLE001 — per-entry degradation
            entry["error"] = repr(e)[:200]
        if with_retraces and "error" not in entry:
            try:
                entry["retraces"] = measure_retraces(c)
                entry["retrace_budget"] = int(c.retrace_budget)
            except Exception as e:  # noqa: BLE001
                entry["error"] = repr(e)[:200]
        block[c.name] = entry
    return block


def check_retrace_budgets(contracts=None) -> list[dict]:
    """[{name, retraces, budget}] for every contract whose second call
    retraces above its declared budget (empty = all clean). The tier-1
    gate (tests/test_profiling.py) asserts this is empty."""
    if contracts is None:
        from ..analysis.registry import default_contracts

        contracts = default_contracts()
    bad = []
    for c in contracts:
        got = measure_retraces(c)
        if got > c.retrace_budget:
            bad.append({"name": c.name, "retraces": got,
                        "budget": int(c.retrace_budget)})
    return bad


@contextmanager
def profiler_trace(log_dir: str | None):
    """jax.profiler capture around the block when `log_dir` is set; a
    plain passthrough otherwise (and when the profiler is unavailable,
    e.g. a stripped jax build)."""
    if not log_dir:
        yield
        return
    try:
        import jax.profiler
        ctx = jax.profiler.trace(log_dir)
    except Exception:
        yield
        return
    with ctx:
        yield


# ------------------------------------------------------- trace export


def chrome_trace(curves: dict, heartbeat_ms: float, t0_ms: float = 0.0,
                 pid: int = 0, name: str = "trial") -> dict:
    """Render flight-recorder curves as Chrome-trace JSON (perfetto loads
    it directly). One "X" (complete) slice per heartbeat carries every
    channel value in args; scalar channels additionally get "C" counter
    tracks so perfetto draws them as time series. `ts`/`dur` are
    microseconds per the trace-event spec; sim time is milliseconds."""
    curves = {k: np.asarray(v) for k, v in curves.items()}
    steps = min((c.shape[0] for c in curves.values()), default=0)
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": name}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "heartbeats"}},
    ]
    for i in range(steps):
        ts = (t0_ms + i * heartbeat_ms) * 1000.0
        args = {}
        for k, c in curves.items():
            v = c[i]
            args[k] = (float(v) if np.ndim(v) == 0
                       else [float(x) for x in np.ravel(v)])
        events.append({
            "name": "heartbeat", "ph": "X", "ts": ts,
            "dur": heartbeat_ms * 1000.0, "pid": pid, "tid": 0,
            "args": {"hb": i, **args},
        })
        for k, c in curves.items():
            if np.ndim(c[i]) == 0:
                events.append({
                    "name": k, "ph": "C", "ts": ts, "pid": pid,
                    "args": {"value": float(c[i])},
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
