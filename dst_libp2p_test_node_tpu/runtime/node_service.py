"""Control & injection layer (reference L4) + live metric serving (L5).

The reference exposes, per node process:
  - HTTP POST /publish on :8645 accepting {"topic","msgSize","version"}
    (gossipsub-queues/main.nim:192-240; go-test-node/main.go:84-151;
    rust-test-node/src/main.rs:146-221);
  - GET /health and /ready returning "ok" (kad-dht/helpers.nim:94-117,
    service-discovery/helpers.nim:138-161);
  - Prometheus GET /metrics on :8008 (env.nim:39-55);
  - in-Shadow metric persistence: append the node's own /metrics scrape to
    metrics_pod-<id>.txt every 5 min, start staggered by myId*60 ms
    (env.nim:58-73, env.go:118-146, env.rs:114-152).

TPU-native shape: one process hosts the WHOLE simulated network, so the
service wraps a Simulator. /publish lands mid-simulation and is buffered
into a queue the simulation loop drains at round granularity — faithful to
the reference, whose injector itself quantizes at inter_message_delay
granularity (shadow/topogen.py:129; SURVEY.md §7 "host/device control
plane"). HTTP handler threads never touch JAX: they enqueue requests and
read a metrics snapshot the pump loop refreshes under a lock.

The Rust node routes /publish through an mpsc channel into its single swarm
event loop (main.rs:466-516) — the same design, channel = PublishQueue.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..config.env import HTTP_CONTROL_PORT, PROMETHEUS_PORT, NodeConfig
from .metrics import NodeMetrics
from .simulator import MixDegradedError


@dataclass
class PublishRequest:
    topic: str
    msg_size: int
    version: int = 1


class PublishQueue:
    """Thread-safe publish buffer between HTTP handlers and the sim loop."""

    def __init__(self) -> None:
        self._q: queue.Queue[PublishRequest] = queue.Queue()

    def put(self, req: PublishRequest) -> None:
        self._q.put(req)

    def drain(self) -> list[PublishRequest]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out


def _json_response(handler, code: int, payload: dict) -> None:
    body = json.dumps(payload, allow_nan=False).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def _text_response(handler, code: int, text: str, ctype="text/plain") -> None:
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class NodeService:
    """Host-side control plane over the device-side simulation."""

    def __init__(
        self,
        simulator,
        cfg: NodeConfig | None = None,
        control_port: int = HTTP_CONTROL_PORT,
        metrics_port: int = PROMETHEUS_PORT,
    ) -> None:
        self.sim = simulator
        self.cfg = cfg or NodeConfig()
        self.topic = self.cfg.topic
        # multi-topic backing sim (runtime/multitopic.py): /publish routes by
        # the request's topic name; single-topic sims accept only cfg.topic.
        # ONE flag drives every multi-topic branch (pump dispatch, topic
        # whitelist, metric labels/aggregation).
        self._multitopic = hasattr(simulator, "topic_index")
        self._topics = (tuple(simulator.cfg.topics) if self._multitopic
                        else (self.topic,))
        self.publishes = PublishQueue()
        # counters carry one topic label; with several topics the honest
        # label is the joined list (per-topic mesh gauges are emitted with
        # their real names separately)
        self.metrics = NodeMetrics(
            muxer=self.cfg.muxer, peer_id=str(self.cfg.my_id),
            topic=",".join(self._topics))
        self._metrics_text = self.metrics.render()
        self._lock = threading.Lock()
        self._control_port = control_port
        self._metrics_port = metrics_port
        self._servers: list[ThreadingHTTPServer] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self.lines_out: list[str] = []  # latency lines emitted by pump()

    # ------------------------------------------------------------- servers

    @property
    def control_port(self) -> int:
        return self._control_port

    @property
    def metrics_port(self) -> int:
        return self._metrics_port

    def start(self) -> None:
        svc = self

        class ControlHandler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path in ("/health", "/ready"):
                    _text_response(self, 200, "ok")
                else:
                    _text_response(self, 404, "Not Found")

            def do_POST(self):
                if self.path != "/publish":
                    _text_response(self, 404, "Not Found")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    req = PublishRequest(
                        topic=body["topic"],
                        msg_size=int(body["msgSize"]),
                        version=int(body.get("version", 1)),
                    )
                except Exception as e:  # malformed request -> 400 (main.nim:227-230)
                    _json_response(
                        self, 400, {"status": "error", "message": str(e)})
                    return
                if req.topic not in svc._topics:
                    # "Topic not joined" (main.go:107-110)
                    _text_response(self, 500, "Topic not joined")
                    return
                t_pub = svc.enqueue_publish(req)
                _json_response(self, 200, {
                    "status": "success",
                    "message": f"Message published at time {t_pub}",
                })

            def do_PUT(self):
                _text_response(self, 405, "Method Not Supported")

        class MetricsHandler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    _text_response(
                        self, 200, svc.metrics_text(),
                        ctype="text/plain; version=0.0.4")
                else:
                    _text_response(self, 404, "Not Found")

        for port_attr, handler in (
            ("_control_port", ControlHandler), ("_metrics_port", MetricsHandler)
        ):
            server = ThreadingHTTPServer(("0.0.0.0", getattr(self, port_attr)), handler)
            setattr(self, port_attr, server.server_address[1])  # resolve port 0
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            self._servers.append(server)
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for s in self._servers:
            s.shutdown()
            s.server_close()
        self._servers.clear()

    # --------------------------------------------------------------- plumbing

    def enqueue_publish(self, req: PublishRequest) -> int:
        """Accept a /publish; returns the quantized injection time (ns scale
        matches the reference's 'published at time <ns>' reply). Metrics are
        counted at pump() time, when the publish actually succeeds or fails —
        counting here too would double-book failed requests."""
        self.publishes.put(req)
        t_ms = float(self.sim.state.t_ms)
        return int(t_ms * 1e6)  # ns

    def metrics_text(self) -> str:
        with self._lock:
            return self._metrics_text

    def pump(self, advance_ms: float = 0.0) -> int:
        """One service round: advance sim time, drain queued publishes, emit
        latency lines, refresh the metrics snapshot. Returns #published."""
        if advance_ms > 0:
            self.sim.advance(advance_ms)
        n_pub = 0
        n_real = (self.sim.n_peers if self._multitopic else self.sim.params.n)
        view = self.cfg.my_id % n_real  # the simulated peer this node's
        # metrics report for (my_id can exceed n via PEER_ID_OFFSET)
        for req in self.publishes.drain():
            try:
                if self._multitopic:
                    rec = self.sim.publish(req.topic, view,
                                           msg_size=req.msg_size)
                else:
                    rec = self.sim.publish(view, msg_size=req.msg_size)
            except (ValueError, MixDegradedError):
                # bad request parameters or a degraded mix network. (A view
                # peer not subscribed to the topic is NOT an error: it
                # publishes through the gossipsub v1.1 fanout path. Engine/
                # runtime failures like XlaRuntimeError propagate — a dead
                # device must crash the service, not count as failed
                # publishes.)
                self.metrics.on_publish_request(ok=False)
                continue
            self.metrics.on_publish_request(ok=True)
            n_pub += 1
            # the stdout contract (main.nim:150): one line per receiver
            for peer, d in zip(rec.receivers, rec.delays_ms_int):
                self.lines_out.append(f"{rec.msg_id} milliseconds: {d}")
                if peer == view:
                    self.metrics.on_delivery(float(d), chunks=self.sim.cfg.topo.num_frags)
        self.metrics.fill_from_sim(self.sim, view)
        # flight-recorder window (Simulator.record_telemetry): export the
        # latest per-heartbeat curves as the dst_sim_round_* family
        tel = getattr(self.sim, "last_telemetry", None)
        if tel:
            self.metrics.fill_from_telemetry(tel)
        with self._lock:
            self._metrics_text = self.metrics.render()
        return n_pub

    # ----------------------------------------------------- metric persistence

    def store_metrics_loop(
        self, out_dir: str = ".", interval_s: float = 300.0,
        stagger: bool = True, max_iters: int | None = None,
    ) -> threading.Thread:
        """Background metrics_pod-<id>.txt appender (env.nim:58-73). Like the
        Rust node we snapshot the registry directly instead of scraping
        localhost (env.rs:114-152 — the Shadow-friendly variant)."""
        my_id = self.cfg.my_id

        def loop():
            time.sleep(my_id * 0.060 if stagger else 0.0)  # myId*60ms stagger
            i = 0
            while not self._stop.is_set():
                with open(f"{out_dir}/metrics_pod-{my_id}.txt", "a") as f:
                    f.write(self.metrics_text())
                i += 1
                if max_iters is not None and i >= max_iters:
                    return
                if self._stop.wait(interval_s):
                    return

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)
        return t


def serve_forever(
    simulator, cfg: NodeConfig, *,
    control_port: int = HTTP_CONTROL_PORT,
    metrics_port: int = PROMETHEUS_PORT,
    time_scale: float = 1.0,
    tick_s: float = 1.0,
    duration_s: float | None = None,
    store_metrics_dir: str | None = None,
    out=None,
) -> NodeService:
    """Run the node service loop: each wall tick advances the simulation by
    tick_s * time_scale seconds of simulated time and drains the publish
    queue. `duration_s` bounds the loop (None = until KeyboardInterrupt)."""
    svc = NodeService(
        simulator, cfg, control_port=control_port, metrics_port=metrics_port)
    svc.start()
    if store_metrics_dir is not None:
        svc.store_metrics_loop(store_metrics_dir)
    t_end = None if duration_s is None else time.monotonic() + duration_s
    try:
        while t_end is None or time.monotonic() < t_end:
            t0 = time.monotonic()
            svc.pump(advance_ms=tick_s * time_scale * 1000.0)
            if out is not None:
                for line in svc.lines_out:
                    print(line, file=out)
            svc.lines_out.clear()  # always drain — a long-lived service must
            # not accumulate one string per receiver per message forever
            leftover = tick_s - (time.monotonic() - t0)
            if leftover > 0 and svc._stop.wait(leftover):
                break
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
    return svc
