"""Resident service runtime (reference L4 control + L5 serving, grown into
a long-lived multi-tenant node: ISSUE 13).

The reference exposes, per node process:
  - HTTP POST /publish on :8645 accepting {"topic","msgSize","version"}
    (gossipsub-queues/main.nim:192-240; go-test-node/main.go:84-151;
    rust-test-node/src/main.rs:146-221);
  - GET /health and /ready returning "ok" (kad-dht/helpers.nim:94-117,
    service-discovery/helpers.nim:138-161);
  - Prometheus GET /metrics on :8008 (env.nim:39-55);
  - in-Shadow metric persistence: append the node's own /metrics scrape to
    metrics_pod-<id>.txt every 5 min, start staggered by myId*60 ms
    (env.nim:58-73, env.go:118-146, env.rs:114-152).

TPU-native shape: one process hosts the WHOLE simulated network, so the
service wraps a Simulator. /publish lands mid-simulation and is buffered
into a queue the simulation loop drains at round granularity — faithful to
the reference, whose injector itself quantizes at inter_message_delay
granularity (shadow/topogen.py:129; SURVEY.md §7 "host/device control
plane"). HTTP handler threads never touch JAX: they enqueue requests and
read a metrics snapshot the pump loop refreshes under a lock.

What "resident" adds on top of the thin shim (ARCHITECTURE §16):

  - ADMISSION CONTROL: the publish queue is bounded (depth cap + an
    estimated device-time budget fed by an EWMA of measured dispatch
    walls). Overflow is explicit backpressure — HTTP 429 with a
    Retry-After header and a strict-JSON body — never unbounded growth.
  - DEADLINES: each request carries an absolute SIM-TIME deadline (wall
    deadlines would make replay nondeterministic); expired work is shed at
    pop time, before it ever reaches the device.
  - FAIR BATCHING DISPATCH: pump() pops a bounded batch round-robin across
    tenants (FIFO within a tenant) and dispatches it against the resident
    compiled programs — one XLA cache, shared by every tenant. Per-tenant
    admission/latency series stream on the dst_service_* family.
  - SUPERVISION (the PR-6 campaign pattern, runtime/campaign.py): device
    dispatch runs under a watchdog timeout with bounded exponential-backoff
    retries; a request that exhausts its budget is QUARANTINED (counted,
    reported degraded in strict JSON) instead of crashing the service.
    Request-level errors (bad params, degraded mix) stay non-retryable.
  - CRASH-SAFE WARM RESTART: periodic checkpoints embed a service sidecar
    (pending queue, fairness cursor, counters) next to the SimState
    snapshot (runtime/checkpoint.py FORMAT_VERSION 10, tolerant load), so
    SIGKILL + NodeService.restore resumes bit-identically for replayed
    requests.
  - GRACEFUL SHUTDOWN: serve_forever installs SIGTERM/SIGINT handlers that
    stop admitting (503 while draining), drain in-flight work under a
    deadline, flush a final checkpoint, and return cleanly.

The Rust node routes /publish through an mpsc channel into its single swarm
event loop (main.rs:466-516) — the same design, channel = PublishQueue.
"""

from __future__ import annotations

import json
import math
import signal
import threading
import time
from collections import deque
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..config.env import HTTP_CONTROL_PORT, PROMETHEUS_PORT, NodeConfig
from .campaign import _call_with_timeout, _FailureInjector
from .metrics import NodeMetrics
from .simulator import MixDegradedError

_INF = float("inf")
DEFAULT_TENANT = "default"


@dataclass
class PublishRequest:
    topic: str
    msg_size: int
    version: int = 1
    tenant: str = DEFAULT_TENANT
    # absolute SIM-TIME deadline (ms); +inf = none. Sim time, not wall
    # time, so shed decisions replay deterministically after a restart.
    deadline_ms: float = _INF
    t_enq_ms: float = 0.0     # sim time at admission
    t_enq_wall: float = 0.0   # host wall at admission (latency observation)


def _req_to_json(r: PublishRequest) -> dict:
    return {
        "topic": r.topic, "msg_size": int(r.msg_size),
        "version": int(r.version), "tenant": r.tenant,
        # strict JSON: +inf deadline is encoded as null
        "deadline_ms": (None if math.isinf(r.deadline_ms)
                        else float(r.deadline_ms)),
        "t_enq_ms": float(r.t_enq_ms),
    }


def _req_from_json(d: dict) -> PublishRequest:
    return PublishRequest(
        topic=d["topic"], msg_size=int(d["msg_size"]),
        version=int(d.get("version", 1)),
        tenant=d.get("tenant", DEFAULT_TENANT),
        deadline_ms=(_INF if d.get("deadline_ms") is None
                     else float(d["deadline_ms"])),
        t_enq_ms=float(d.get("t_enq_ms", 0.0)),
    )


class PublishQueue:
    """Bounded admission-controlled publish buffer between HTTP handlers and
    the sim loop (replaces the unbounded queue.Queue buffer, whose put/drain
    pair also raced: a put landing mid-drain could be returned by BOTH the
    in-flight drain and the next one under get_nowait retries).

    Every operation holds one lock, so drain/take_batch are atomic snapshots.
    Structure: one FIFO deque per tenant + a stable tenant ring for
    round-robin fairness. `offer` rejects (returns False) once the depth cap
    or the estimated device-time budget is exceeded — the caller turns that
    into HTTP 429 + Retry-After."""

    def __init__(self, max_depth: int = 1024,
                 device_ms_budget: float = 0.0) -> None:
        self.max_depth = int(max_depth)
        self.device_ms_budget = float(device_ms_budget)
        self._lock = threading.Lock()
        self._tenants: dict[str, deque[PublishRequest]] = {}
        self._ring: list[str] = []   # tenant names in first-seen order
        self._cursor = 0             # next ring position round-robin serves
        self.dropped = 0             # admission rejections (backpressure)

    def offer(self, req: PublishRequest, est_ms: float = 0.0) -> bool:
        """Admit or reject atomically. est_ms: the dispatcher's EWMA of one
        request's device wall — depth * est_ms above device_ms_budget (> 0)
        rejects even below the depth cap."""
        with self._lock:
            depth = sum(len(q) for q in self._tenants.values())
            over_depth = depth >= self.max_depth
            over_budget = (
                self.device_ms_budget > 0.0 and est_ms > 0.0
                and (depth + 1) * est_ms > self.device_ms_budget)
            if over_depth or over_budget:
                self.dropped += 1
                return False
            q = self._tenants.get(req.tenant)
            if q is None:
                q = self._tenants[req.tenant] = deque()
                self._ring.append(req.tenant)
            q.append(req)
            return True

    def put(self, req: PublishRequest) -> bool:
        """Legacy surface of the unbounded queue; now an admission check."""
        return self.offer(req)

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._tenants.values())

    def take_batch(
        self, max_batch: int | None, now_ms: float,
    ) -> tuple[list[PublishRequest], list[PublishRequest]]:
        """Atomically pop up to max_batch requests, round-robin one per
        tenant per lap (FIFO within a tenant), shedding any popped request
        whose sim-time deadline has passed. Returns (batch, shed); the
        fairness cursor persists across calls (and across restarts — it is
        checkpointed)."""
        batch: list[PublishRequest] = []
        shed: list[PublishRequest] = []
        with self._lock:
            if not self._ring:
                return batch, shed
            n_t = len(self._ring)
            idle_laps = 0
            while (max_batch is None or len(batch) < max_batch) \
                    and idle_laps < n_t:
                name = self._ring[self._cursor % n_t]
                self._cursor = (self._cursor + 1) % n_t
                q = self._tenants.get(name)
                if not q:
                    idle_laps += 1
                    continue
                idle_laps = 0
                req = q.popleft()
                if req.deadline_ms < now_ms:
                    shed.append(req)
                else:
                    batch.append(req)
            return batch, shed

    def drain(self) -> list[PublishRequest]:
        """Atomic take-everything (fair order, no shedding)."""
        batch, _ = self.take_batch(None, -_INF)
        return batch

    # --------------------------------------------------- checkpoint surface

    def snapshot(self) -> dict:
        """JSON-safe pending-queue state for the service checkpoint sidecar:
        re-admitted verbatim on restore, so a kill between flush and dispatch
        loses nothing that was already accepted."""
        with self._lock:
            return {
                "ring": list(self._ring),
                "cursor": self._cursor,
                "dropped": self.dropped,
                "pending": {t: [_req_to_json(r) for r in q]
                            for t, q in self._tenants.items()},
            }

    def restore(self, snap: dict | None) -> None:
        if not snap:
            return
        with self._lock:
            self._ring = list(snap.get("ring", []))
            self._cursor = int(snap.get("cursor", 0))
            self.dropped = int(snap.get("dropped", 0))
            self._tenants = {
                t: deque(_req_from_json(d) for d in reqs)
                for t, reqs in snap.get("pending", {}).items()}
            # wall clocks don't survive the process (t_enq_wall is not
            # serialized); re-stamp admission wall time so the restored
            # requests' sojourn measures time-in-system since restore
            # instead of the raw monotonic epoch
            now_wall = time.monotonic()
            for q in self._tenants.values():
                for r in q:
                    r.t_enq_wall = now_wall
            for t in self._tenants:
                if t not in self._ring:
                    self._ring.append(t)


@dataclass
class ServiceConfig:
    """Resident-runtime knobs (admission, batching, supervision, restart).
    The defaults keep the thin-shim behavior of the pre-resident service:
    a large bound, no deadlines, no checkpointing — existing callers see
    the same contract, just with the unbounded-growth bug closed."""

    max_queue_depth: int = 1024
    device_ms_budget: float = 0.0     # est. queued device ms cap; 0 = off
    default_deadline_ms: float = 0.0  # relative sim ms per request; 0 = none
    max_batch: int = 64               # requests per pump round
    # "batched": stack each same-shape group of the fair batch into seed
    # columns and run it as ONE compiled device dispatch (ISSUE 14);
    # "sequential": one dispatch per request — the pinned bit-equality
    # reference, same pattern as answer_queue_mode="serial". Both modes
    # produce bit-identical record streams (tests/test_service_runtime.py).
    dispatch_mode: str = "batched"
    dispatch_timeout_s: float = 0.0   # watchdog per attempt; 0 = off
    max_retries: int = 1
    retry_backoff_s: float = 0.05     # doubles per retry (campaign pattern)
    inject_failures: int = 0          # first K dispatch attempts raise (CI)
    checkpoint_path: str | None = None
    checkpoint_every: int = 0         # pump rounds between flushes; 0 = off
    drain_deadline_s: float = 5.0     # graceful-shutdown drain budget
    retry_after_s: float = 1.0        # advertised 429/503 Retry-After

    def validate(self) -> None:
        if self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.dispatch_mode not in ("batched", "sequential"):
            raise ValueError(
                f"dispatch_mode must be 'batched' or 'sequential', "
                f"got {self.dispatch_mode!r}")
        for k in ("device_ms_budget", "default_deadline_ms",
                  "dispatch_timeout_s", "retry_backoff_s",
                  "drain_deadline_s", "retry_after_s"):
            if getattr(self, k) < 0.0:
                raise ValueError(f"{k} must be >= 0")
        if self.max_retries < 0 or self.inject_failures < 0:
            raise ValueError("max_retries/inject_failures must be >= 0")
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")


def _json_response(handler, code: int, payload: dict,
                   headers: dict | None = None) -> None:
    body = json.dumps(payload, allow_nan=False).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in (headers or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)


def _text_response(handler, code: int, text: str, ctype="text/plain") -> None:
    body = text.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class NodeService:
    """Host-side control plane over the device-side simulation."""

    def __init__(
        self,
        simulator,
        cfg: NodeConfig | None = None,
        control_port: int = HTTP_CONTROL_PORT,
        metrics_port: int = PROMETHEUS_PORT,
        service: ServiceConfig | None = None,
    ) -> None:
        self.sim = simulator
        self.cfg = cfg or NodeConfig()
        self.topic = self.cfg.topic
        self.svc_cfg = service or ServiceConfig()
        self.svc_cfg.validate()
        # multi-topic backing sim (runtime/multitopic.py): /publish routes by
        # the request's topic name; single-topic sims accept only cfg.topic.
        # ONE flag drives every multi-topic branch (pump dispatch, topic
        # whitelist, metric labels/aggregation).
        self._multitopic = hasattr(simulator, "topic_index")
        self._topics = (tuple(simulator.cfg.topics) if self._multitopic
                        else (self.topic,))
        self.publishes = PublishQueue(
            max_depth=self.svc_cfg.max_queue_depth,
            device_ms_budget=self.svc_cfg.device_ms_budget)
        # counters carry one topic label; with several topics the honest
        # label is the joined list (per-topic mesh gauges are emitted with
        # their real names separately)
        self.metrics = NodeMetrics(
            muxer=self.cfg.muxer, peer_id=str(self.cfg.my_id),
            topic=",".join(self._topics))
        self._metrics_text = self.metrics.render()
        self._lock = threading.Lock()
        self._control_port = control_port
        self._metrics_port = metrics_port
        self._servers: list[ThreadingHTTPServer] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self.lines_out: list[str] = []  # latency lines emitted by pump()
        # ------- resident-runtime state -------
        self.counters: dict[str, int] = {
            "admitted": 0, "rejected": 0, "shed_deadline": 0,
            "dispatched": 0, "dispatch_failures": 0, "retries": 0,
            "quarantined": 0, "checkpoint_flushes": 0, "restarts": 0,
            "batch_splits": 0, "device_dispatches": 0,
        }
        self.degraded = False
        self.draining = False
        self.last_error: str | None = None
        self.pump_rounds = 0
        self.max_depth_seen = 0
        self._ewma_ms = 0.0  # EWMA of one request's DEVICE wall (ms)
        # per-pump-round accumulators feeding the EWMA: device-call wall
        # only (no retry-backoff sleeps — those over-shed healthy tenants),
        # amortized over the requests the round processed
        self._round_device_ms = 0.0
        self._round_reqs = 0
        self._round_dispatches = 0
        self._injector = _FailureInjector(self.svc_cfg.inject_failures)
        # (tenant, sojourn_ms) of recent dispatches — the load driver's
        # latency source; bounded so a long-lived service cannot grow it
        self.latencies: deque[tuple[str, float]] = deque(maxlen=4096)

    # ------------------------------------------------------------- servers

    @property
    def control_port(self) -> int:
        return self._control_port

    @property
    def metrics_port(self) -> int:
        return self._metrics_port

    def start(self) -> None:
        svc = self

        class ControlHandler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path in ("/health", "/ready"):
                    _text_response(self, 200, "ok")
                elif self.path == "/service":
                    _json_response(self, 200, svc.service_status())
                elif self.path == "/telemetry":
                    _json_response(self, 200, svc.telemetry_status())
                else:
                    _text_response(self, 404, "Not Found")

            def do_POST(self):
                if self.path != "/publish":
                    _text_response(self, 404, "Not Found")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    req = PublishRequest(
                        topic=body["topic"],
                        msg_size=int(body["msgSize"]),
                        version=int(body.get("version", 1)),
                        tenant=str(body.get("tenant", DEFAULT_TENANT)),
                        deadline_ms=(
                            float(body["deadlineMs"]) if "deadlineMs" in body
                            else _INF),
                    )
                except Exception as e:  # malformed request -> 400 (main.nim:227-230)
                    _json_response(
                        self, 400, {"status": "error", "message": str(e)})
                    return
                if req.topic not in svc._topics:
                    # "Topic not joined" (main.go:107-110)
                    _text_response(self, 500, "Topic not joined")
                    return
                code, payload, headers = svc.submit(req)
                _json_response(self, code, payload, headers)

            def do_PUT(self):
                _text_response(self, 405, "Method Not Supported")

        class MetricsHandler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    _text_response(
                        self, 200, svc.metrics_text(),
                        ctype="text/plain; version=0.0.4")
                else:
                    _text_response(self, 404, "Not Found")

        for port_attr, handler in (
            ("_control_port", ControlHandler), ("_metrics_port", MetricsHandler)
        ):
            server = ThreadingHTTPServer(("0.0.0.0", getattr(self, port_attr)), handler)
            setattr(self, port_attr, server.server_address[1])  # resolve port 0
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            self._servers.append(server)
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for s in self._servers:
            s.shutdown()
            s.server_close()
        self._servers.clear()

    # --------------------------------------------------------------- admission

    def _sim_now(self) -> float:
        return float(self.sim.state.t_ms) + self.sim._hb_carry_ms

    def submit(
        self, req: PublishRequest,
    ) -> tuple[int, dict, dict]:
        """Admission control for one request: (http_code, strict-JSON body,
        extra headers). 200 = queued for the next pump round; 429 = shed by
        backpressure (depth or device-time budget) with Retry-After; 503 =
        the service is draining for shutdown and admits nothing."""
        retry_hdr = {"Retry-After":
                     str(int(math.ceil(self.svc_cfg.retry_after_s)))}
        if self.draining:
            self.counters["rejected"] += 1
            self.metrics.service_dropped.inc(labels={"reason": "draining"})
            return 503, {"status": "draining",
                         "retry_after_s": self.svc_cfg.retry_after_s}, retry_hdr
        now = self._sim_now()
        req.t_enq_ms = now
        req.t_enq_wall = time.monotonic()
        if math.isinf(req.deadline_ms):
            if self.svc_cfg.default_deadline_ms > 0.0:
                req.deadline_ms = now + self.svc_cfg.default_deadline_ms
        else:
            # deadlines arrive RELATIVE sim-ms (a client can't know the
            # sim clock); stored absolute so shedding replays exactly
            req.deadline_ms = now + req.deadline_ms
        if not self.publishes.offer(req, est_ms=self._ewma_ms):
            self.counters["rejected"] += 1
            self.metrics.service_dropped.inc(
                labels={"reason": "backpressure"})
            return 429, {
                "status": "rejected", "reason": "backpressure",
                "queue_depth": self.publishes.depth(),
                "retry_after_s": self.svc_cfg.retry_after_s,
            }, retry_hdr
        self.counters["admitted"] += 1
        self.metrics.service_admitted.inc(labels={"tenant": req.tenant})
        return 200, {
            "status": "success",
            "message": f"Message published at time {int(now * 1e6)}",
        }, {}

    def enqueue_publish(self, req: PublishRequest) -> int:
        """Accept a /publish; returns the quantized injection time (ns scale
        matches the reference's 'published at time <ns>' reply). Metrics are
        counted at pump() time, when the publish actually succeeds or fails —
        counting here too would double-book failed requests. Raises on
        backpressure (the HTTP surface maps that to 429 via submit)."""
        code, payload, _ = self.submit(req)
        if code != 200:
            raise RuntimeError(f"publish not admitted: {payload['status']}")
        return int(req.t_enq_ms * 1e6)  # ns

    def metrics_text(self) -> str:
        with self._lock:
            return self._metrics_text

    def service_status(self) -> dict:
        """Strict-JSON runtime status (GET /service)."""
        return {
            "status": "draining" if self.draining else "serving",
            "degraded": self.degraded,
            "queue_depth": self.publishes.depth(),
            "max_queue_depth": self.svc_cfg.max_queue_depth,
            "max_depth_seen": self.max_depth_seen,
            "est_dispatch_ms": round(self._ewma_ms, 3),
            "dispatch_mode": self.svc_cfg.dispatch_mode,
            "pump_rounds": self.pump_rounds,
            "counters": dict(self.counters),
            "last_error": self.last_error,
            "topics": list(self._topics),
        }

    def telemetry_status(self) -> dict:
        """Strict-JSON flight-recorder window (GET /telemetry): the latest
        armed advance()'s per-heartbeat tel_* curves — the same series the
        scrape exports as dst_sim_round_* gauges, but as whole curves per
        channel so a tenant can stream the live per-heartbeat trajectory
        instead of polling one point per scrape. Empty curves until
        record_telemetry arms the recorder and an advance runs."""
        import numpy as np

        from .summarize import sanitize_nonfinite

        tel = getattr(self.sim, "last_telemetry", None) or {}
        curves = {
            k: sanitize_nonfinite(np.asarray(v, dtype=np.float64).tolist())
            for k, v in tel.items() if k.startswith("tel_")
        }
        return {
            "armed": getattr(self.sim, "_telemetry", None) is not None,
            "sim_t_ms": self._sim_now(),
            "pump_rounds": self.pump_rounds,
            "heartbeats": (len(next(iter(curves.values())))
                           if curves else 0),
            "curves": curves,
        }

    # --------------------------------------------------------------- dispatch

    def _note_device_ms(self, wall_ms: float, n_requests: int) -> None:
        """Account one device call's wall toward this round's admission
        estimate. Only device work is counted — retry-backoff sleeps are
        deliberately excluded, so a retry storm no longer inflates the
        queued-device-ms budget and over-sheds healthy tenants."""
        self._round_device_ms += wall_ms
        self._round_reqs += n_requests
        self._round_dispatches += 1
        self.counters["device_dispatches"] += 1
        self.metrics.service_dispatches.inc()

    def _commit_publish(self, req: PublishRequest, rec, view: int) -> None:
        """Success bookkeeping for one served request (shared by the
        sequential and batched paths): per-tenant sojourn, delivery
        metrics, and the stdout latency-line contract."""
        self.metrics.on_publish_request(ok=True)
        self.counters["dispatched"] += 1
        sojourn_ms = (time.monotonic() - req.t_enq_wall) * 1000.0
        self.latencies.append((req.tenant, sojourn_ms))
        self.metrics.service_latency.observe(
            sojourn_ms, labels={"tenant": req.tenant})
        # the stdout contract (main.nim:150): one line per receiver
        for peer, d in zip(rec.receivers, rec.delays_ms_int):
            self.lines_out.append(f"{rec.msg_id} milliseconds: {d}")
            if peer == view:
                self.metrics.on_delivery(
                    float(d), chunks=self.sim.cfg.topo.num_frags)

    def _dispatch(self, req: PublishRequest, view: int) -> int:
        """One supervised device dispatch: watchdog timeout + bounded
        exponential-backoff retries + quarantine (the PR-6 campaign
        pattern). Returns 1 on a successful publish. Request-level errors
        (bad params, degraded mix) are terminal — retrying a deterministic
        rejection wastes device time."""
        sup = self.svc_cfg

        def run():
            if self._multitopic:
                return self.sim.publish(req.topic, view,
                                        msg_size=req.msg_size)
            return self.sim.publish(view, msg_size=req.msg_size)

        last_err = None
        for attempt in range(sup.max_retries + 1):
            if attempt > 0:
                time.sleep(sup.retry_backoff_s * (2 ** (attempt - 1)))
                self.counters["retries"] += 1
                self.metrics.service_retries.inc()
                self.degraded = True
            try:
                self._injector.maybe_fail()
                t0 = time.monotonic()
                rec = _call_with_timeout(run, sup.dispatch_timeout_s)
            except (ValueError, MixDegradedError):
                # bad request parameters or a degraded mix network. (A view
                # peer not subscribed to the topic is NOT an error: it
                # publishes through the gossipsub v1.1 fanout path.)
                self.metrics.on_publish_request(ok=False)
                return 0
            except Exception as e:  # noqa: BLE001 — the supervisor IS the handler
                last_err = e
                self.counters["dispatch_failures"] += 1
                self.metrics.service_failures.inc()
                continue
            self._note_device_ms((time.monotonic() - t0) * 1000.0, 1)
            self._commit_publish(req, rec, view)
            return 1
        # retry budget exhausted: quarantine the poison request; the service
        # stays up and reports itself degraded instead of crashing
        self.counters["quarantined"] += 1
        self.metrics.service_quarantined.inc()
        self.degraded = True
        self.last_error = repr(last_err)
        self.metrics.on_publish_request(ok=False)
        return 0

    def _group_key(self, req: PublishRequest, view: int):
        """Static-shape bucket of one request: msg_size + the fanout flag
        (an unsubscribed view publishes through the gossipsub v1.1 fanout
        path, a different compiled program). The topic is NOT part of the
        key — a multi-topic batch stacks topics as row indices, so the eth2
        att-subnet lane batches across its subnets."""
        if self._multitopic:
            ti = self.sim.topic_index(req.topic)
            fanout = not bool(self.sim.subscribed_np[ti][view])
        else:
            fanout = not bool(self.sim._subscribed_np[view])
        return (req.msg_size, fanout)

    def _group_batch(self, batch, view: int):
        """MODE-INVARIANT grouping of the fair batch: groups keyed by
        static shape bucket in first-appearance order, FIFO within a
        group. Both dispatch modes iterate these same groups in the same
        order — dispatch_mode only changes how one group executes (a
        request loop vs one stacked scan) — which is what makes
        batched == sequential bit-identity hold for ALL traffic, not just
        single-bucket rounds."""
        groups: list[list[PublishRequest]] = []
        index: dict = {}
        for req in batch:
            k = self._group_key(req, view)
            i = index.get(k)
            if i is None:
                index[k] = len(groups)
                groups.append([req])
            else:
                groups[i].append(req)
        return groups

    def _dispatch_batch(self, reqs: list, view: int) -> int:
        """One same-bucket group as ONE supervised device dispatch
        (ISSUE 14). Failure handling lifts the PR-6 per-seed split to
        batch granularity: a failed batch is bisected and each half
        re-dispatched, so only the poison request is ever quarantined —
        never the batch. Single-request groups take the per-request
        retry/quarantine path directly (keeps sequential-mode counter
        semantics for the B=1 degenerate case)."""
        if len(reqs) == 1:
            return self._dispatch(reqs[0], view)
        sim = self.sim
        if (getattr(sim, "mix_params", None) is not None
                or sim.mesh is not None
                or not hasattr(sim, "publish_batch")):
            # mix routing and peer-sharded grids keep the per-publish path
            # (Simulator.publish_batch documents why); so do foreign sims
            return sum(self._dispatch(r, view) for r in reqs)
        sup = self.svc_cfg

        def run():
            if self._multitopic:
                return sim.publish_batch(
                    [(r.topic, view) for r in reqs],
                    msg_size=reqs[0].msg_size, pad_to=sup.max_batch)
            return sim.publish_batch(
                [view] * len(reqs), msg_size=reqs[0].msg_size,
                pad_to=sup.max_batch)

        try:
            self._injector.maybe_fail()
            t0 = time.monotonic()
            recs = _call_with_timeout(run, sup.dispatch_timeout_s)
        except (ValueError, MixDegradedError):
            # request-level rejection at batch granularity can't name the
            # culprit: re-dispatch each request alone (terminal per
            # request — _dispatch never retries these)
            return sum(self._dispatch(r, view) for r in reqs)
        except Exception as e:  # noqa: BLE001 — the supervisor IS the handler
            self.counters["dispatch_failures"] += 1
            self.metrics.service_failures.inc()
            self.counters["batch_splits"] += 1
            self.metrics.service_splits.inc()
            self.degraded = True
            self.last_error = repr(e)
            mid = len(reqs) // 2
            return (self._dispatch_batch(reqs[:mid], view)
                    + self._dispatch_batch(reqs[mid:], view))
        self._note_device_ms((time.monotonic() - t0) * 1000.0, len(reqs))
        for r, rec in zip(reqs, recs):
            self._commit_publish(r, rec, view)
        return len(reqs)

    def pump(self, advance_ms: float = 0.0) -> int:
        """One service round: advance sim time, pop a fair bounded batch
        (shedding expired requests), group it by static-shape bucket, and
        dispatch each group under the supervisor — one stacked device
        dispatch per group in batched mode, one per request in sequential
        mode — then emit latency lines, refresh the metrics snapshot, and
        flush the periodic checkpoint. Returns #published."""
        if advance_ms > 0:
            self.sim.advance(advance_ms)
        depth_before = self.publishes.depth()
        self.max_depth_seen = max(self.max_depth_seen, depth_before)
        now = self._sim_now()
        batch, shed = self.publishes.take_batch(self.svc_cfg.max_batch, now)
        for req in shed:
            self.counters["shed_deadline"] += 1
            self.metrics.service_dropped.inc(labels={"reason": "deadline"})
        n_pub = 0
        n_real = (self.sim.n_peers if self._multitopic else self.sim.params.n)
        view = self.cfg.my_id % n_real  # the simulated peer this node's
        # metrics report for (my_id can exceed n via PEER_ID_OFFSET)
        self._round_device_ms = 0.0
        self._round_reqs = 0
        self._round_dispatches = 0
        for group in self._group_batch(batch, view):
            if self.svc_cfg.dispatch_mode == "batched":
                n_pub += self._dispatch_batch(group, view)
            else:
                for req in group:
                    n_pub += self._dispatch(req, view)
        if batch:
            self.metrics.service_batches.inc()
            if self._round_reqs:
                # admission budget estimator: device wall per REQUEST —
                # amortized over the round's requests, sleeps excluded
                per_ms = self._round_device_ms / self._round_reqs
                self._ewma_ms = (per_ms if self._ewma_ms == 0.0
                                 else 0.8 * self._ewma_ms + 0.2 * per_ms)
            if self._round_dispatches:
                self.metrics.service_batch_factor.set(
                    self._round_reqs / self._round_dispatches)
        self.metrics.fill_from_sim(self.sim, view)
        # flight-recorder window (Simulator.record_telemetry): export the
        # latest per-heartbeat curves as the dst_sim_round_* family
        tel = getattr(self.sim, "last_telemetry", None)
        if tel:
            self.metrics.fill_from_telemetry(tel)
        self._fill_service_gauges()
        with self._lock:
            self._metrics_text = self.metrics.render()
        self.pump_rounds += 1
        every = self.svc_cfg.checkpoint_every
        if self.svc_cfg.checkpoint_path and every > 0 \
                and self.pump_rounds % every == 0:
            self.flush_checkpoint()
        return n_pub

    def _fill_service_gauges(self) -> None:
        m = self.metrics
        m.service_queue_depth.set(self.publishes.depth())
        m.service_degraded.set(1.0 if self.degraded else 0.0)
        m.service_draining.set(1.0 if self.draining else 0.0)
        m.service_restarts.set(float(self.counters["restarts"]))
        m.service_est_dispatch.set(self._ewma_ms)

    # ----------------------------------------------------- warm restart

    def _service_meta(self) -> dict:
        """The checkpoint sidecar: everything the resident runtime needs to
        resume exactly — pending queue + fairness cursor (lost work would
        break replay bit-identity), counters, and the dispatch EWMA."""
        return {
            "pump_rounds": self.pump_rounds,
            "counters": dict(self.counters),
            "degraded": self.degraded,
            "last_error": self.last_error,
            "ewma_ms": self._ewma_ms,
            "queue": self.publishes.snapshot(),
        }

    def flush_checkpoint(self, path: str | None = None) -> str | None:
        """Atomic snapshot of sim + service state (checkpoint.py writes
        tmp -> os.replace, so SIGKILL mid-flush keeps the previous good
        snapshot)."""
        from .checkpoint import save_checkpoint

        path = path or self.svc_cfg.checkpoint_path
        if not path:
            return None
        save_checkpoint(self.sim, path, service_meta=self._service_meta())
        self.counters["checkpoint_flushes"] += 1
        self.metrics.service_checkpoints.inc()
        return path

    @classmethod
    def restore(
        cls,
        path: str,
        cfg: NodeConfig | None = None,
        control_port: int = HTTP_CONTROL_PORT,
        metrics_port: int = PROMETHEUS_PORT,
        service: ServiceConfig | None = None,
        mesh=None,
    ) -> "NodeService":
        """Warm restart from a service checkpoint: rebuild the simulator
        bit-exactly (runtime/checkpoint.py) and re-admit the pending queue,
        counters, and fairness cursor from the sidecar. Replayed requests
        then produce results identical to an uninterrupted run."""
        from .checkpoint import load_checkpoint, load_service_meta

        sim = load_checkpoint(path, mesh=mesh)
        meta = load_service_meta(path)
        svc = cls(sim, cfg, control_port=control_port,
                  metrics_port=metrics_port, service=service)
        svc.pump_rounds = int(meta.get("pump_rounds", 0))
        saved = meta.get("counters", {})
        for k in svc.counters:
            if k in saved:
                svc.counters[k] = int(saved[k])
        svc.counters["restarts"] = int(saved.get("restarts", 0)) + 1
        svc.degraded = bool(meta.get("degraded", False))
        svc.last_error = meta.get("last_error")
        svc._ewma_ms = float(meta.get("ewma_ms", 0.0))
        svc.publishes.restore(meta.get("queue"))
        # the scrape survives the restart too: re-base the service counters
        # so rate() over a kill sees a monotone series, not a reset to zero
        m = svc.metrics
        for series, key, lab in (
            (m.service_dropped, "rejected", {"reason": "backpressure"}),
            (m.service_dropped, "shed_deadline", {"reason": "deadline"}),
            (m.service_failures, "dispatch_failures", None),
            (m.service_retries, "retries", None),
            (m.service_quarantined, "quarantined", None),
            (m.service_checkpoints, "checkpoint_flushes", None),
            (m.service_splits, "batch_splits", None),
            (m.service_dispatches, "device_dispatches", None),
        ):
            v = svc.counters.get(key, 0)
            if v:
                series.inc(v, labels=lab)
        if svc.counters["admitted"]:
            m.service_admitted.inc(svc.counters["admitted"],
                                   labels={"tenant": DEFAULT_TENANT})
        svc._fill_service_gauges()
        with svc._lock:
            svc._metrics_text = m.render()
        return svc

    # ----------------------------------------------------- graceful shutdown

    def begin_drain(self) -> None:
        """Stop admitting (submit answers 503); in-flight work keeps
        draining via pump() until shutdown's deadline."""
        self.draining = True
        self.metrics.service_draining.set(1.0)

    def shutdown(self) -> None:
        """Drain the queue under drain_deadline_s, flush a final checkpoint,
        stop the HTTP servers. Idempotent; serve_forever's signal path."""
        self.begin_drain()
        deadline = time.monotonic() + self.svc_cfg.drain_deadline_s
        while self.publishes.depth() > 0 and time.monotonic() < deadline:
            self.pump()
        self.flush_checkpoint()
        self.stop()

    # ----------------------------------------------------- metric persistence

    def store_metrics_loop(
        self, out_dir: str = ".", interval_s: float = 300.0,
        stagger: bool = True, max_iters: int | None = None,
    ) -> threading.Thread:
        """Background metrics_pod-<id>.txt appender (env.nim:58-73). Like the
        Rust node we snapshot the registry directly instead of scraping
        localhost (env.rs:114-152 — the Shadow-friendly variant)."""
        my_id = self.cfg.my_id

        def loop():
            time.sleep(my_id * 0.060 if stagger else 0.0)  # myId*60ms stagger
            i = 0
            while not self._stop.is_set():
                with open(f"{out_dir}/metrics_pod-{my_id}.txt", "a") as f:
                    f.write(self.metrics_text())
                i += 1
                if max_iters is not None and i >= max_iters:
                    return
                if self._stop.wait(interval_s):
                    return

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)
        return t


def serve_forever(
    simulator, cfg: NodeConfig, *,
    control_port: int = HTTP_CONTROL_PORT,
    metrics_port: int = PROMETHEUS_PORT,
    time_scale: float = 1.0,
    tick_s: float = 1.0,
    duration_s: float | None = None,
    store_metrics_dir: str | None = None,
    out=None,
    service: ServiceConfig | None = None,
    resume_from: str | None = None,
    install_signal_handlers: bool = True,
) -> NodeService:
    """Run the node service loop: each wall tick advances the simulation by
    tick_s * time_scale seconds of simulated time and drains the publish
    queue. `duration_s` bounds the loop (None = until SIGTERM/SIGINT).

    SIGTERM/SIGINT (installed only on the main thread) switch the service
    into draining — no new admissions (503), queued work dispatched under
    ServiceConfig.drain_deadline_s, one final checkpoint flushed — then the
    loop returns normally, so the process exits 0 instead of dying mid-
    request. `resume_from`: warm-restart from this service checkpoint
    instead of using `simulator` (crash-recovery path; the file must
    exist)."""
    import os

    if resume_from is not None:
        if not os.path.exists(resume_from):
            raise FileNotFoundError(
                f"resume checkpoint not found: {resume_from}")
        svc = NodeService.restore(
            resume_from, cfg, control_port=control_port,
            metrics_port=metrics_port, service=service)
    else:
        svc = NodeService(
            simulator, cfg, control_port=control_port,
            metrics_port=metrics_port, service=service)
    svc.start()
    if store_metrics_dir is not None:
        svc.store_metrics_loop(store_metrics_dir)
    stop_requested = threading.Event()

    def _on_signal(signum, frame):
        stop_requested.set()

    old_handlers = {}
    if install_signal_handlers \
            and threading.current_thread() is threading.main_thread():
        for s in (signal.SIGTERM, signal.SIGINT):
            old_handlers[s] = signal.signal(s, _on_signal)
    t_end = None if duration_s is None else time.monotonic() + duration_s
    try:
        while not stop_requested.is_set() \
                and (t_end is None or time.monotonic() < t_end):
            t0 = time.monotonic()
            svc.pump(advance_ms=tick_s * time_scale * 1000.0)
            if out is not None:
                for line in svc.lines_out:
                    print(line, file=out)
            svc.lines_out.clear()  # always drain — a long-lived service must
            # not accumulate one string per receiver per message forever
            leftover = tick_s - (time.monotonic() - t0)
            if leftover > 0 and (stop_requested.wait(min(leftover, 0.05))
                                 or svc._stop.is_set()):
                break
    except KeyboardInterrupt:
        pass
    finally:
        # graceful teardown on ANY exit (signal, duration elapsed, error):
        # stop admitting, drain with a deadline, flush the final checkpoint
        svc.shutdown()
        if out is not None:
            for line in svc.lines_out:
                print(line, file=out)
        svc.lines_out.clear()
        for s, h in old_handlers.items():
            signal.signal(s, h)
    return svc
