"""DHT adversary cohorts: poison the discovery layer, starve the mesh.

GossipSub's attack-resilience story (arXiv:2007.02754) assumes a healthy
discovery layer feeding the mesh fresh peers; pub/sub-at-scale systems
(Topiary, arXiv:2312.06800) show discovery is the actual soft underbelly.
This module is the Kademlia-side mirror of ops/adversary.py: three attack
families as compiled mask/key transforms over ops/kad.KadState, composed by
the campaign machinery (runtime/campaign.py) with the GossipSub attack
window so one sweep answers "when the lookup layer is adversarial, how long
does the mesh take to heal?".

Attack families (all combinable, armed per-flag on DhtAdversaryParams):

  lookup eclipse     attacker origins answer FIND_NODE with a poisoned
                     shortlist drawn from a coordinated SYBIL DIRECTORY —
                     the attacker cohort's ids ranked closest to the victim
                     key by construction. The poison rides the python-level
                     hook in ops/kad._find_node_impl: the benign lookup's
                     traced program is untouched.
  rtable poisoning   sybil inserts squat bucket slots via kad.rtable_insert
                     (first-come-keep is the reference's LRU-without-ping
                     policy — squatting is free). `rtable_poison_budget`
                     gives the closed-form per-bucket occupancy ceiling the
                     measured poison fraction must respect.
  sybil clustering   attacker node keys are re-minted inside the victim's
                     keyspace prefix, so xor_bitlen ranks them into the
                     victim's tightest buckets and every honest lookup near
                     the victim walks straight into the cohort.

Arming idiom (ops/faults.py / ops/telemetry.py): the params dataclass is
frozen/hashable, cohort material is drawn host-side from seeded
SeedSequences (zero device PRNG), and every disabled path literally
delegates to the existing runner — same jit cache entry, bit-identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import kad
from .kad import K_RESP, KEY_WORDS, KadState, _find_node_impl


@dataclass(frozen=True)
class DhtAdversaryParams:
    """DHT-layer adversary + discovery wiring knobs (frozen => hashable =>
    usable as a jit static argument, like AdversaryParams/FaultParams).

    `discovery` arms the benign wiring alone: mesh repair's re-dial path
    draws candidates from a (healthy) find_node shortlist when the PX pool
    is exhausted. The three attack flags each imply the wiring (an attacked
    DHT that nothing reads would measure nothing), so `enabled` is the
    union. All defaults OFF: DhtAdversaryParams() composes into a campaign
    as a no-op and the campaign delegates to the pre-DHT runners."""

    discovery: bool = False        # DHT-backed re-dial candidates (benign)
    lookup_eclipse: bool = False   # poisoned FIND_NODE responses
    rtable_poison: bool = False    # sybil bucket-slot squatting
    sybil_cluster: bool = False    # attacker keys minted near the victim
    # sybil inserts pushed into every peer's table (rtable_poison)
    poison_per_peer: int = 8
    # shared key prefix length, bits, for sybil_cluster key minting
    cluster_prefix_bits: int = 16
    # recovery-window round at which the DHT heals (attacked lookups give
    # way to honest ones for the remaining rounds); -1 = never heals
    heal_hb: int = -1
    # sybil directory width for the eclipse response (K_RESP is plenty;
    # wider only pads)
    directory_size: int = 64
    # campaign-side KadState shape: small buckets keep the (N, B, K) tables
    # affordable at campaign N (three such arrays ride the state)
    n_buckets: int = 16
    k_bucket: int = 8
    bootstraps: int = 2
    # benign self-lookup waves that populate tables before the attack
    warmup_waves: int = 2
    # lookup depth for warmup and repair-pool lookups
    lookup_rounds: int = 3
    # kad.evict_failed retry budget for campaign-side waves (satellite:
    # one failed round must not evict for free)
    evict_max_fails: int = 1
    evict_backoff_ms: float = 0.0

    @property
    def attacked(self) -> bool:
        return self.lookup_eclipse or self.rtable_poison or self.sybil_cluster

    @property
    def enabled(self) -> bool:
        return self.discovery or self.attacked

    def validate(self) -> None:
        if self.poison_per_peer < 1:
            raise ValueError("poison_per_peer must be >= 1")
        if not (0 <= self.cluster_prefix_bits <= 32 * KEY_WORDS):
            raise ValueError("cluster_prefix_bits outside [0, KEY_BITS]")
        if self.directory_size < 1:
            raise ValueError("directory_size must be >= 1")
        if self.n_buckets < 1 or self.k_bucket < 1:
            raise ValueError("n_buckets/k_bucket must be >= 1")
        if self.bootstraps < 1:
            raise ValueError("bootstraps must be >= 1")
        if self.warmup_waves < 1:
            raise ValueError("warmup_waves must be >= 1")
        if self.lookup_rounds < 1:
            raise ValueError("lookup_rounds must be >= 1")
        if self.evict_max_fails < 1:
            raise ValueError("evict_max_fails must be >= 1")
        if self.evict_backoff_ms < 0.0:
            raise ValueError("evict_backoff_ms must be >= 0")


# ------------------------------------------------------------------ cohorts


def mint_sybil_keys(keys: np.ndarray, attacker: np.ndarray, victim: int,
                    prefix_bits: int, seed: int) -> np.ndarray:
    """Sybil key clustering: re-mint every attacker's node key inside the
    victim's keyspace prefix (top `prefix_bits` bits copied from the victim,
    the rest uniform). xor_bitlen then ranks the cohort into the victim's
    tightest buckets — the classic keyspace-squatting placement. Pure
    host-side numpy on a fresh SeedSequence lane (zero device PRNG)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x5B11]))
    out = keys.copy()
    att = np.nonzero(attacker)[0]
    if att.size == 0 or prefix_bits == 0:
        return out
    rand = rng.integers(0, 1 << 32, size=(att.size, KEY_WORDS),
                        dtype=np.uint32)
    for w in range(KEY_WORDS):
        hi = min(32, max(0, prefix_bits - 32 * w))
        mask = np.uint32(((0xFFFFFFFF << (32 - hi)) & 0xFFFFFFFF) if hi
                         else 0)
        out[att, w] = (keys[victim, w] & mask) | (rand[:, w] & ~mask)
    return out


def sybil_directory(keys: np.ndarray, attacker: np.ndarray, victim: int,
                    size: int) -> np.ndarray:
    """The eclipse cohort's coordinated answer sheet: attacker ids ordered
    by XOR distance to the VICTIM's key (-1 padded to `size`). Every
    attacker responder serves FIND_NODE from this directory instead of its
    routing table, so poisoned shortlists contain zero honest entries and
    the entries rank closest-by-construction when sybil_cluster minted the
    keys into the victim's prefix."""
    out = np.full((size,), -1, dtype=np.int32)
    att = np.nonzero(attacker)[0]
    if att.size == 0:
        return out
    k = min(size, att.size)
    order = kad.true_closest(keys[att], keys[victim], k=k)
    out[:k] = att[order].astype(np.int32)
    return out


def poison_candidates(n: int, attacker: np.ndarray, per_peer: int,
                      seed: int) -> np.ndarray:
    """(N, per_peer) sybil insert batch for rtable poisoning: each peer is
    pushed a random sample of attacker ids (with replacement — duplicates
    are dropped by _insert_one's within-batch dedup, modeling imperfect
    coordination). Host-side numpy, fresh SeedSequence lane."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xD47]))
    att = np.nonzero(attacker)[0]
    if att.size == 0:
        return np.full((n, per_peer), -1, dtype=np.int32)
    return rng.choice(att, size=(n, per_peer)).astype(np.int32)


def rtable_poison_budget(per_peer: int, n_buckets: int, k_bucket: int,
                         prefix_bits: int = 0) -> float:
    """Closed-form ceiling on the routing-table poison fraction one insert
    wave of `per_peer` sybils per peer can reach (the heartbeats_to_graylist
    idiom: the budget the measured occupancy is tested against).

    For uniform sybil keys, the probability a sybil lands in bucket b
    (distance bit-length KEY_BITS - b) is 2^-(b+1), with the final bucket
    absorbing the tail mass 2^-(B-1). Clustered keys sharing `prefix_bits`
    top bits with the victim shift that mass: buckets shallower than the
    prefix get zero, deeper buckets see the distribution restarted at the
    prefix boundary. Each bucket caps at k_bucket slots; the budget is the
    capped expected occupancy over the whole (B, K) table. An actual table
    can only do worse: honest entries already hold slots (first-come-keep)
    and duplicate sybils collapse."""
    total = 0.0
    p = min(prefix_bits, n_buckets - 1)
    for b in range(n_buckets):
        if b < p:
            mass = 0.0
        elif b == n_buckets - 1:
            mass = 2.0 ** -(b - p)
        else:
            mass = 2.0 ** -(b - p + 1)
        total += min(per_peer * mass, float(k_bucket))
    return min(total / (n_buckets * k_bucket), 1.0)


def rtable_poison_frac(state: KadState, attacker: np.ndarray) -> float:
    """Measured poison fraction: share of occupied honest-row routing-table
    slots that point at attacker ids (host-side; the campaign's
    rtable_poison_frac report/metrics channel)."""
    rt = np.asarray(state.rtable)
    honest = ~np.asarray(attacker, dtype=bool)
    rows = rt[honest]
    occ = rows >= 0
    total = int(occ.sum())
    if total == 0:
        return 0.0
    att = np.asarray(attacker, dtype=bool)
    return float(att[np.clip(rows, 0, None)][occ].sum() / total)


# ----------------------------------------------------------- attacked lookup


@partial(jax.jit, static_argnames=("rounds", "shortlist"))
def _find_node_attacked(state, origins, targets, stage, lat_ms, attacker,
                        directory, rounds, shortlist):
    # the directory is a flat (D,) id list; _closest_from_table flattens
    # its table argument, so a (1, D) view serves directly as the cohort's
    # shared answer table
    poison0 = jax.vmap(
        lambda t: kad._closest_from_table(
            directory.reshape(1, -1), state.keys, t, K_RESP)
    )(targets)
    return _find_node_impl(state, origins, targets, stage, lat_ms,
                           rounds, shortlist, attacker=attacker,
                           poison0=poison0)


def find_node_attacked(
    state: KadState,
    origins: jnp.ndarray,
    targets: jnp.ndarray,
    stage: jnp.ndarray,
    lat_ms: jnp.ndarray,
    dht: DhtAdversaryParams,
    attacker: jnp.ndarray | None = None,
    directory: jnp.ndarray | None = None,
    rounds: int = 6,
    shortlist: int = 32,
) -> tuple[kad.LookupResult, KadState]:
    """find_node with the lookup-eclipse family armed: attacker responders
    answer from the sybil directory. Disabled (or no cohort material)
    literally delegates to kad.find_node — same function object, same jit
    cache entry, bit-identical (tests/test_dht_adversary.py pins this)."""
    if not dht.lookup_eclipse or attacker is None or directory is None:
        return kad.find_node(state, origins, targets, stage, lat_ms,
                             rounds=rounds, shortlist=shortlist)
    return _find_node_attacked(state, origins, targets, stage, lat_ms,
                               attacker, directory, rounds, shortlist)


# ------------------------------------------------------------ campaign setup


def build_attacked_dht(n: int, seed: int, dht: DhtAdversaryParams,
                       attacker: np.ndarray, victim: int,
                       stage: jnp.ndarray, lat_ms: jnp.ndarray
                       ) -> tuple[KadState, jnp.ndarray | None]:
    """One trial's DHT, built under attack: init (keys minted into the
    victim's prefix when sybil_cluster), bootstrap seeding, `warmup_waves`
    self-lookup waves (eclipsed when lookup_eclipse — discovery warmup IS
    the infection vector), then the rtable_poison insert wave. Returns
    (KadState, sybil directory or None). Deterministic per (seed, params):
    checkpoint resume re-derives it instead of snapshotting the tables."""
    has_att = bool(np.asarray(attacker).any())
    kstate = kad.init_kad_state(n, n_buckets=dht.n_buckets,
                                k_bucket=dht.k_bucket, seed=seed)
    if dht.sybil_cluster and has_att:
        keys = mint_sybil_keys(np.asarray(kstate.keys), attacker, victim,
                               dht.cluster_prefix_bits, seed)
        kstate = kstate.replace(keys=jnp.asarray(keys))
    boots = jnp.arange(min(dht.bootstraps, n), dtype=jnp.int32)
    kstate = kad.seed_bootstraps(kstate, boots)
    directory = None
    att_dev = None
    if dht.lookup_eclipse and has_att:
        directory = jnp.asarray(sybil_directory(
            np.asarray(kstate.keys), attacker, victim, dht.directory_size))
        att_dev = jnp.asarray(attacker)
    origins = jnp.arange(n, dtype=jnp.int32)
    for _ in range(dht.warmup_waves):
        res, kstate = find_node_attacked(
            kstate, origins, kstate.keys, stage, lat_ms, dht,
            attacker=att_dev, directory=directory,
            rounds=dht.lookup_rounds)
        kstate = kad.evict_failed(kstate, origins, res.closest,
                                  max_fails=dht.evict_max_fails,
                                  backoff_base_ms=dht.evict_backoff_ms)
    if dht.rtable_poison and has_att:
        cands = poison_candidates(n, attacker, dht.poison_per_peer, seed)
        kstate = kad.rtable_insert(kstate, origins, jnp.asarray(cands))
    return kstate, directory


def dht_repair_pool(kstate: KadState, dht: DhtAdversaryParams,
                    stage: jnp.ndarray, lat_ms: jnp.ndarray,
                    attacker: jnp.ndarray | None = None,
                    directory: jnp.ndarray | None = None,
                    healed: bool = False
                    ) -> tuple[jnp.ndarray, KadState]:
    """The repair controller's second candidate source: every peer runs a
    FIND_NODE self-lookup over the (possibly attacked) DHT and dials from
    the resulting (N, K_RESP) shortlist when its PX pool is exhausted
    (ops/repair.repair_round's dht_pool). `healed=True` forces the honest
    lookup — the heal-after-eclipse leg — over the SAME evolved tables, so
    residual rtable poison still shows through the honest walk."""
    n = kstate.rtable.shape[0]
    origins = jnp.arange(n, dtype=jnp.int32)
    res, kstate = find_node_attacked(
        kstate, origins, kstate.keys, stage, lat_ms, dht,
        attacker=None if healed else attacker,
        directory=None if healed else directory,
        rounds=dht.lookup_rounds)
    pool = jnp.where(res.closest == origins[:, None], -1, res.closest)
    return pool, kstate
