"""The reciprocal-permutation pull — THE hot memory primitive of the engine.

Every protocol exchange in the simulator moves data across the static
directed-edge involution (p, i) <-> (q = conns[p,i], j = rev[p,i]): GRAFT /
PRUNE reciprocity in the heartbeat, the per-iteration offer pull of the
dissemination fixpoint, and the post-fixpoint accounting. Semantically each
is `out[q, j] = vals[conns[q,j], rev[q,j]]` — a gather through two (N, C)
index vectors.

TPU performance note (measured at N=100k, C=40 on v5e):
  - two-index-vector gather `vals[conns, rev]`:        ~45 ms (4M random
    scalar loads; XLA's general gather path)
  - flattened one-index gather over the (N*C,) table:  ~34 ms
  - whole-ROW gather `vals[conns]` + fused iota-select: ~11 ms

Row gathers are embedding-style lookups (contiguous C-element reads) that
the TPU pipeline handles well; the slot-select then happens in registers via
an iota comparison that XLA fuses into the gather consumer. We trade C x
read amplification for contiguity and win ~4x. The iota mask is built
inline (never materialized as an (N, C, C) constant) so peak memory stays
O(N*C*C) only inside the fused loop body.

The sharded fixpoint (parallel/exchange.py converge_sharded) deliberately
does NOT use this: its per-iteration cross-shard traffic is the (N,) time
vector alone, and the pull there is against receiver-local constants.
"""

from __future__ import annotations

import jax.numpy as jnp

INF = jnp.float32(3.4e38)

# Peak-memory budget for the (N, C, C) row-gather intermediate. The last
# axis pads to the 128-lane TPU tile, so the real footprint is
# N*C*max(128,C)*itemsize bytes. Within budget the row gather is the fastest
# formulation (11 ms f32 / at 100k,C=40 vs 45 ms for the naive 2-index
# gather). Beyond it — e.g. f32 at 1M peers would be a 20 GiB intermediate —
# the memory-light 2-index gather WINS outright (732 ms/pull at 1M vs
# ~2.7 s for a sequentially-chunked row gather: chunk serialization costs
# more than the random scalar loads), so large pulls simply fall back.
_MAX_INTERMEDIATE_BYTES = 6 * 1024**3
_LANE = 128


def exceeds_budget(dtype, conns_shape, batch_factor: int = 1) -> bool:
    """The dispatch decision, exposed for tests: would the padded row-gather
    intermediate for this pull exceed the memory budget?

    `batch_factor`: outer vmap width (fragments, topics). Trace-time shapes
    are per-instance — the REAL allocation is batch_factor times the
    per-instance intermediate, so the dispatch must account for it or a
    9-fragment publish would blow an in-budget 2 GiB pull up to 18 GiB."""
    n, c = conns_shape[-2], conns_shape[-1]
    itemsize = 1 if dtype == jnp.bool_ else jnp.dtype(dtype).itemsize
    padded = n * c * max(_LANE, c) * itemsize * max(batch_factor, 1)
    return padded > _MAX_INTERMEDIATE_BYTES


def _row_pull(vals, conns, rev, select, fallback, batch_factor):
    """Size-dispatched core. `select(rows, sel)` reduces the gathered rows;
    `fallback(q, r)` is the direct 2-index gather used when the row-gather
    intermediate would not fit the budget (see exceeds_budget)."""
    c = conns.shape[-1]
    if exceeds_budget(vals.dtype, conns.shape, batch_factor):
        return fallback(jnp.clip(conns, 0), jnp.clip(rev, 0))
    rows = vals[..., jnp.clip(conns, 0), :]   # (..., N, C, C) contiguous
    sel = jnp.arange(c) == jnp.clip(rev, 0)[..., None]
    return select(rows, sel)


def reciprocal_pull_bool(
    edge_mask: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray,
    batch_factor: int = 1,
) -> jnp.ndarray:
    """out[q, j] = edge_mask[conns[q,j], rev[q,j]]; False on invalid slots."""
    out = _row_pull(
        edge_mask, conns, rev,
        lambda rows, sel: (rows & sel).any(axis=-1),
        lambda q, r: edge_mask[q, r], batch_factor)
    return out & (conns >= 0) & (rev >= 0)


def neighbor_pull_bool(
    per_peer: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray,
    batch_factor: int = 1,
) -> jnp.ndarray:
    """out[q, j] = per_peer[conns[q,j]] (False on invalid slots) — a per-PEER
    table lookup through the neighbor index. Same row-contiguity trick: the
    (N,) vector broadcasts to a (N, C) table that is constant along slots,
    so pulling any slot of the neighbor's row (we use the reverse slot, which
    is always in range) yields the per-peer value."""
    table = jnp.broadcast_to(per_peer[:, None], conns.shape)
    return reciprocal_pull_bool(table, conns, rev, batch_factor)


def neighbor_pull_min(
    per_peer: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray,
    batch_factor: int = 1,
) -> jnp.ndarray:
    """out[q, j] = per_peer[conns[q,j]] for floats; INF on invalid slots."""
    table = jnp.broadcast_to(per_peer[:, None], conns.shape)
    return reciprocal_pull_min(table, conns, rev, batch_factor)


def reciprocal_pull_min(
    vals: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray,
    batch_factor: int = 1,
) -> jnp.ndarray:
    """out[q, j] = vals[conns[q,j], rev[q,j]] for float vals; INF on invalid
    slots. Exactly-one-hot select via masked min (INF-safe: the fill value
    is the identity of min and also the 'absent' sentinel)."""
    out = _row_pull(
        vals, conns, rev,
        lambda rows, sel: jnp.where(sel, rows, INF).min(axis=-1),
        lambda q, r: vals[q, r], batch_factor)
    return jnp.where((conns >= 0) & (rev >= 0), out, INF)
