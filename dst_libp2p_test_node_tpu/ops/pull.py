"""The reciprocal-permutation pull — THE hot memory primitive of the engine.

Every protocol exchange in the simulator moves data across the static
directed-edge involution (p, i) <-> (q = conns[p,i], j = rev[p,i]): GRAFT /
PRUNE reciprocity in the heartbeat, the per-iteration offer pull of the
dissemination fixpoint, and the post-fixpoint accounting. Semantically each
is `out[q, j] = vals[conns[q,j], rev[q,j]]` — a gather through two (N, C)
index vectors.

TPU performance note (measured at N=100k, C=40 on v5e):
  - two-index-vector gather `vals[conns, rev]`:        ~45 ms (4M random
    scalar loads; XLA's general gather path)
  - flattened one-index gather over the (N*C,) table:  ~34 ms
  - whole-ROW gather `vals[conns]` + fused iota-select: ~11 ms

Row gathers are embedding-style lookups (contiguous C-element reads) that
the TPU pipeline handles well; the slot-select then happens in registers via
an iota comparison that XLA fuses into the gather consumer. We trade C x
read amplification for contiguity and win ~4x. The iota mask is built
inline (never materialized as an (N, C, C) constant) so peak memory stays
O(N*C*C) only inside the fused loop body.

The sharded fixpoint (parallel/exchange.py converge_sharded) deliberately
does NOT use this: its per-iteration cross-shard traffic is the (N,) time
vector alone, and the pull there is against receiver-local constants.
"""

from __future__ import annotations

import jax.numpy as jnp

INF = jnp.float32(3.4e38)


def reciprocal_pull_bool(
    edge_mask: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray
) -> jnp.ndarray:
    """out[q, j] = edge_mask[conns[q,j], rev[q,j]]; False on invalid slots."""
    c = conns.shape[-1]
    rows = edge_mask[jnp.clip(conns, 0)]                 # (N, C, C) row gather
    sel = jnp.arange(c) == jnp.clip(rev, 0)[..., None]   # fused iota compare
    out = (rows & sel).any(axis=-1)
    return out & (conns >= 0) & (rev >= 0)


def neighbor_pull_bool(
    per_peer: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray
) -> jnp.ndarray:
    """out[q, j] = per_peer[conns[q,j]] (False on invalid slots) — a per-PEER
    table lookup through the neighbor index. Same row-contiguity trick: the
    (N,) vector broadcasts to a (N, C) table that is constant along slots,
    so pulling any slot of the neighbor's row (we use the reverse slot, which
    is always in range) yields the per-peer value."""
    table = jnp.broadcast_to(per_peer[:, None], conns.shape)
    return reciprocal_pull_bool(table, conns, rev)


def neighbor_pull_min(
    per_peer: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray
) -> jnp.ndarray:
    """out[q, j] = per_peer[conns[q,j]] for floats; INF on invalid slots."""
    table = jnp.broadcast_to(per_peer[:, None], conns.shape)
    return reciprocal_pull_min(table, conns, rev)


def reciprocal_pull_min(
    vals: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray
) -> jnp.ndarray:
    """out[q, j] = vals[conns[q,j], rev[q,j]] for float vals; INF on invalid
    slots. Exactly-one-hot select via masked min (INF-safe: the fill value
    is the identity of min and also the 'absent' sentinel)."""
    c = conns.shape[-1]
    rows = vals[jnp.clip(conns, 0)]
    sel = jnp.arange(c) == jnp.clip(rev, 0)[..., None]
    out = jnp.where(sel, rows, INF).min(axis=-1)
    return jnp.where((conns >= 0) & (rev >= 0), out, INF)
