"""Adversarial perturbations: the v1.1 attack scenarios as on-device masks.

"GossipSub: Attack-Resilient Message Propagation in the Filecoin and ETH2.0
Networks" (arXiv:2007.02754) evaluates the v1.1 score function against a
small canon of attacks. This module expresses that canon inside the engine's
existing fixed-shape algebra — every attacker behavior is a masked (N,)/(N, C)
op riding the same reciprocal-pull involution and the same dissemination
fixpoint as benign traffic, so a 100k-peer attack round costs the same order
as a benign heartbeat and NOTHING here loops over peers in Python:

  sybil_graft_flood   attacker rows force-graft every valid edge each
                      heartbeat (plus the censorship behavior below — sybils
                      contribute nothing). Honest peers answer with the v1.1
                      defense: a re-GRAFT of an edge that is backed off or
                      already meshed is a protocol violation that accrues the
                      behaviour-penalty counter.
  ihave_spam          attacker rows announce `spam_ihaves_per_hb` bogus ids
                      to every valid edge each heartbeat; honest peers IWANT
                      the unseen ids and the answers never come (broken
                      IWANT promises -> the same penalty counter).
  iwant_spam          the amplification dual: attacker rows REQUEST
                      `spam_iwants_per_hb` ids per valid edge each
                      heartbeat. Honest peers answer requests from
                      not-yet-graylisted edges, and each answer occupies
                      the shared uplink for `iwant_answer_ms` — the
                      answer-queue exhaustion lands in
                      SimState.uplink_free_ms, the SAME carry the
                      dissemination fixpoint serializes publishes through,
                      so spam directly delays the next publish. Unsolicited
                      IWANTs accrue the penalty counter once per spammed
                      edge per heartbeat, so scoring eventually stops the
                      bleeding (a graylisted requester is refused).
  censorship          in-mesh attackers silently refuse to forward: a
                      per-edge DELIVERY drop mask (censor_mask) folded into
                      disseminate's `survive` exactly like the graylist
                      gate — distinct from `survive_loss`, so lost_tx keeps
                      counting network losses only.
  eclipse_publisher   the attacker cohort is drawn from the publisher's
                      connected neighbors and the publisher's mesh row is
                      overwritten with attacker edges only (eclipse_setup);
                      with flood_publish off, the first publishes die inside
                      the cohort until scoring evicts it.
  cold_boot_join      the graft-flood scenario started from the un-warmed
                      t=0 state: the mesh must FORM while under attack.

Penalty plumbing. The engine's score model is the v1.1 subset the reference
actually configures (P2 firstMessageDeliveries + the slow-peer penalty
counter, ops/state.py score()). The slow-peer counter is libp2p's negative-
weighted "non-negative counter x weight < 0" shape — exactly the shape of
v1.1's P7 behaviour penalty — so attack violations accrue into
`state.slow_penalty` and the full defense chain downstream is the EXISTING
one: score() -> gossip/publish thresholds -> graylist delivery gating in
disseminate -> score-ranked prune + score>=0 graft eligibility in
heartbeat_step. Campaign configs must set slow_peer_penalty_weight < 0 or
the static `thresholds_can_bind` gate compiles every defense out
(ops/disseminate.py) — attack_gossipsub() in runtime/campaign.py does.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .heartbeat import heartbeat_step
from .pull import neighbor_pull_bool, reciprocal_pull_bool
from .state import (PX_POOL_WIDTH, AdaptiveCtrl, SimParams, SimState,
                    init_adaptive_ctrl, repair_inert, restore_repair,
                    strip_repair)

SCENARIOS = (
    "sybil_graft_flood",
    "ihave_spam",
    "iwant_spam",
    "censorship",
    "eclipse_publisher",
    "cold_boot_join",
    # the two static-canon stragglers from arXiv:2007.02754 (ROADMAP):
    #   slow_peer_mimicry    the attacker meters its own misbehavior so its
    #                        score in every honest peer's view sits at
    #                        mimic_margin * (G/w) — just ABOVE the graylist
    #                        floor, below the gossip/publish thresholds: it
    #                        contributes nothing, censors everything, and
    #                        the threshold defenses never quite fire.
    #   identity_rotation    graft-flood whose sybils rotate identities
    #                        every rotation_period_hb heartbeats: the honest
    #                        side's per-edge counters (fmd, penalty,
    #                        backoff) reset — a "new peer" on the same
    #                        socket slots — so the accrual race restarts
    #                        before the graylist budget is spent.
    "slow_peer_mimicry",
    "identity_rotation",
)


# Scenarios the adaptive controller composes with: the graft-flood family,
# where the attacker's round behavior is mesh pressure the controller can
# modulate. The spam scenarios have no backoff/mesh feedback loop to adapt
# to, mimicry IS already a (perfect-information) adaptive policy, and
# rotation's scrub cadence would erase the controller's own estimate.
ADAPTIVE_SCENARIOS = ("sybil_graft_flood", "eclipse_publisher",
                      "cold_boot_join")


@dataclass(frozen=True)
class AdaptivePolicy:
    """Static (hashable -> jit static arg) per-round attacker controller
    policy — the adaptive arms race from arXiv:2007.02754 §5 compiled into
    the heartbeat scan. Disabled (the default) the wrappers LITERALLY
    delegate to the static runners: same jit cache entry, bit-identical,
    zero extra PRNG. Armed, a per-attacker controller state (AdaptiveCtrl,
    ops/state.py) rides the scan carry and the attacker reacts to its own
    observables each round:

      regraft      re-graft every edge the moment its backoff expires (and
                   the edge left the mesh) — legal grafts that rebuild
                   attacker mesh share without accruing the behaviour
                   penalty.
      px_poison    answer PX demand with sybil ids: plant attacker ids into
                   the px_pool rows of honest peers adjacent to the cohort
                   (px_poison_per_hb plants per victim per round, rotating
                   through the sorted cohort) — mesh repair's candidate
                   lattice (PX -> DHT -> random) then dials sybils first.
      slot_race    during recovery windows, the attacker cohort runs the
                   repair controller too (run_adaptive_recovery_heartbeats
                   passes actor=everyone) and ACCEPTS inbound dials — it
                   races honest dialers for every slot eviction frees.
      duty_cycle   score-aware throttling: each attacker tracks its own
                   conservative estimate of the worst honest-side penalty
                   counter any of its edges carries and stops flooding
                   whenever one more violation would push its score past
                   throttle_margin * graylist_threshold. The closed-form
                   heartbeats_to_graylist budget becomes inf — the
                   graylist never engages, which is the scenario's finding
                   (the mimicry precedent), not a config error.
    """

    enabled: bool = False
    regraft: bool = True
    px_poison: bool = True
    slot_race: bool = True
    duty_cycle: bool = True
    # duty-cycle setpoint: throttle when the predicted counter would exceed
    # throttle_margin * c_req (c_req = graylist_threshold / slow_weight).
    # Margins close to 1 flood harder but risk graylisting through estimate
    # error; the default leaves 20% headroom.
    throttle_margin: float = 0.8
    # sybil ids planted per victim px_pool row per heartbeat
    px_poison_per_hb: int = 2

    def validate(self, scenario: str | None = None) -> None:
        if not (0.0 < self.throttle_margin < 1.0):
            raise ValueError("throttle_margin must be in (0, 1) — at >= 1 "
                             "the controller graylists itself, defeating "
                             "the duty cycle")
        if not (1 <= self.px_poison_per_hb <= PX_POOL_WIDTH):
            raise ValueError(
                f"px_poison_per_hb must be in [1, {PX_POOL_WIDTH}] "
                f"(the px_pool width), got {self.px_poison_per_hb}")
        if self.enabled and not (self.regraft or self.px_poison
                                 or self.slot_race or self.duty_cycle):
            raise ValueError("adaptive policy is enabled but every behavior "
                             "is off — use enabled=False (the delegating "
                             "path) instead of an armed no-op")
        if self.enabled and scenario is not None \
                and scenario not in ADAPTIVE_SCENARIOS:
            raise ValueError(
                f"adaptive policy composes with {ADAPTIVE_SCENARIOS} only "
                f"(the graft-flood family), not scenario {scenario!r}: the "
                "spam scenarios have no backoff/mesh loop to adapt to, "
                "mimicry is already an adaptive policy, and rotation's "
                "identity scrubs erase the controller's own estimate")


@dataclass(frozen=True)
class AdversaryParams:
    """Static (hashable -> jit static arg) attack-scenario parameters."""

    scenario: str = "sybil_graft_flood"
    # behaviour-penalty counter increment per protocol violation per
    # heartbeat (re-GRAFT of a backed-off/meshed edge; unanswered IWANT)
    violation_penalty: float = 1.0
    # P3-analog: counter increment per publish on a mesh edge whose member
    # silently delivered nothing (censorship_penalty_update)
    censor_penalty: float = 1.0
    # bogus IHAVE ids announced per valid edge per heartbeat (ihave_spam)
    spam_ihaves_per_hb: int = 8
    # unsolicited IWANT ids requested per valid edge per heartbeat
    # (iwant_spam); each answered id occupies the victim's uplink for
    # iwant_answer_ms (the amplification factor)
    spam_iwants_per_hb: int = 16
    iwant_answer_ms: float = 2.0
    # slow_peer_mimicry: pin the attacker's per-edge penalty counter at
    # mimic_margin * c_req (c_req = graylist_threshold / slow_weight), i.e.
    # the score sits at mimic_margin * graylist_threshold — just above the
    # floor for any margin < 1
    mimic_margin: float = 0.9
    # identity_rotation: heartbeats between identity scrubs
    rotation_period_hb: int = 4
    # per-round adaptive controller policy (frozen, so the shared default
    # instance keeps the dataclass a pure static key: every disabled config
    # hashes/compares equal and lands on the same jit cache entry)
    adaptive: AdaptivePolicy = AdaptivePolicy()

    def validate(self) -> None:
        self.adaptive.validate(self.scenario)
        if self.scenario not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {self.scenario!r}; expected one of {SCENARIOS}")
        if self.violation_penalty <= 0.0 or self.censor_penalty < 0.0:
            raise ValueError("violation_penalty must be > 0, censor_penalty >= 0")
        if self.spam_ihaves_per_hb < 1:
            raise ValueError("spam_ihaves_per_hb must be >= 1")
        if self.spam_iwants_per_hb < 1:
            raise ValueError("spam_iwants_per_hb must be >= 1")
        if self.iwant_answer_ms < 0.0:
            raise ValueError("iwant_answer_ms must be >= 0")
        if not (0.0 < self.mimic_margin < 1.0):
            raise ValueError("mimic_margin must be in (0, 1) — at >= 1 the "
                             "mimic graylists itself, defeating the scenario")
        if self.rotation_period_hb < 2:
            raise ValueError("rotation_period_hb must be >= 2 (a period of 1 "
                             "scrubs every round: no accrual ever survives)")

    # scenario -> active behaviors (all derived, keeping the dataclass a
    # pure static key: one flag per scenario would multiply trace keys)
    @property
    def graft_flood(self) -> bool:
        return self.scenario in ("sybil_graft_flood", "eclipse_publisher",
                                 "cold_boot_join", "identity_rotation")

    @property
    def ihave_spam(self) -> bool:
        return self.scenario == "ihave_spam"

    @property
    def iwant_spam(self) -> bool:
        return self.scenario == "iwant_spam"

    @property
    def eclipse(self) -> bool:
        return self.scenario == "eclipse_publisher"

    @property
    def cold_boot(self) -> bool:
        return self.scenario == "cold_boot_join"

    @property
    def slow_mimicry(self) -> bool:
        return self.scenario == "slow_peer_mimicry"

    @property
    def identity_rotation(self) -> bool:
        return self.scenario == "identity_rotation"


def attacker_cohort(
    n: int,
    fraction: float,
    seed: int,
    conns: np.ndarray | None = None,
    publisher: int | None = None,
    eclipse: bool = False,
) -> np.ndarray:
    """(N,) bool attacker membership — host-side TRIAL SETUP (one draw per
    trial, not per peer per round). Deterministic in (seed, fraction).

    `eclipse`: fill the cohort from the publisher's connected neighbors
    first (the attacker placed its sybils on the victim's connection slots),
    then at random; the publisher itself is never an attacker."""
    if not (0.0 <= fraction < 1.0):
        raise ValueError(f"attacker fraction must be in [0, 1), got {fraction}")
    k = int(round(fraction * n))
    mask = np.zeros(n, dtype=bool)
    if k == 0:
        return mask
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, int(fraction * 1e6), 0xAD5E]))
    candidates = np.arange(n)
    if publisher is not None:
        candidates = candidates[candidates != publisher]
    if eclipse:
        if conns is None or publisher is None:
            raise ValueError("eclipse cohort needs conns and publisher")
        nbrs = np.asarray(conns)[publisher]
        nbrs = np.unique(nbrs[nbrs >= 0])
        nbrs = nbrs[nbrs != publisher]
        take = nbrs[:k] if len(nbrs) > k else nbrs
        mask[take] = True
        k -= len(take)
        candidates = candidates[~mask[candidates]]
    if k > 0:
        mask[rng.choice(candidates, size=k, replace=False)] = True
    return mask


def heartbeats_to_graylist(adv: AdversaryParams, params: SimParams) -> float:
    """The DOCUMENTED engagement budget: heartbeats from attack start until
    every violated honest->attacker edge scores below graylist_threshold.

    The penalty counter on a violated edge follows c_k = d*c_{k-1} + p
    (heartbeat decay, then the round's accrual), so after k accrual rounds
    c_k = p(1-d^k)/(1-d). The edge is graylisted when
    slow_weight*c_k <= graylist_threshold, i.e. c_k >= G/w (both negative).
    Violations start on round 2 for graft-flood (round 1's grafts are
    accepted into empty backoff/mesh; every re-graft after violates) and
    round 1 for ihave_spam / iwant_spam. Returns inf when the steady-state
    counter p/(1-d) can never reach the requirement — the campaign should
    treat that as a config error, not wait forever.

    INVARIANT UNDER EVICTION (params.evict). The budget does not move when
    the eviction branch is armed, because eviction swaps WHICH disjunct of
    the violation predicate fires without changing its truth value. Take
    graft-flood: pre-eviction, a flooded edge violates through
    `rx & mesh` (the re-GRAFT of a meshed edge). The eviction PRUNE removes
    the edge from the mesh but — through `_reciprocal_view`, both sides —
    writes `backoff_until = t + prune_backoff_ms`, so from the next round
    the SAME edge violates through `rx & (backoff_until > t)` instead
    (re-GRAFT of a backed-off edge). Since prune_backoff_ms (60 s default)
    spans hundreds of heartbeats and a fresh flood re-arms it, the accrual
    cadence — one violation_penalty per flooded edge per heartbeat — is
    identical, and the c_k = d*c_{k-1} + p recurrence (hence this closed
    form) holds with eviction on or off. tests/test_repair.py pins this by
    bit-comparing the graylisted_frac curves across both modes. The spam
    scenarios never consult mesh/backoff in their violation predicate, so
    they are trivially invariant.

    SLOW-PEER MIMICRY returns inf by construction: the attacker pins its own
    counter at mimic_margin * c_req every round, so the graylist can never
    engage — inf is the scenario's finding, not a config error (run_campaign
    exempts it from the inf-budget guard).

    IDENTITY ROTATION scrubs the honest side's per-edge counters every
    rotation_period_hb rounds. A scrub at round m*period leaves violations
    accruing only in rounds m*period+1 .. (m+1)*period-1, so the graylist
    engages iff the un-rotated budget fits strictly inside one rotation
    cycle; the boundary budget == period is conservatively reported inf
    (engagement there depends on cycle alignment).

    ADAPTIVE DUTY CYCLING (AdaptivePolicy.duty_cycle) returns inf by the
    mimicry precedent: the controller throttles its own flood whenever one
    more violation would push its predicted counter past throttle_margin *
    c_req, and its estimate over-approximates the honest-side counter, so
    the counter is clamped strictly below c_req forever — the budget is
    adaptive in exactly the sense the arms race predicts: infinite. inf is
    the finding, not a config error (run_campaign exempts it from the
    inf-budget guard, like mimicry and rotation)."""
    if adv.slow_mimicry:
        return math.inf
    if adv.adaptive.enabled and adv.adaptive.duty_cycle \
            and params.slow_weight < 0.0:
        return math.inf  # the controller never spends the budget
    if params.slow_weight >= 0.0:
        return math.inf  # thresholds_can_bind is False: defenses compiled out
    c_req = params.graylist_threshold / params.slow_weight
    p = adv.violation_penalty
    d = params.slow_decay
    lead_in = 1.0 if (adv.ihave_spam or adv.iwant_spam) else 2.0
    if c_req <= p:
        base = lead_in  # first accrual already crosses
    else:
        rhs = 1.0 - c_req * (1.0 - d) / p
        if rhs <= 0.0:
            return math.inf
        base = lead_in - 1.0 + math.ceil(math.log(rhs) / math.log(d))
    if adv.identity_rotation and base >= adv.rotation_period_hb:
        return math.inf
    return base


def censor_mask(attacker: jnp.ndarray, conns: jnp.ndarray) -> jnp.ndarray:
    """(N, C) per-edge delivery drop mask: every out-edge of an attacker row.
    Folded into disseminate's `survive` (delivery only — the graylist
    semantics), NOT into `survive_loss`: a withheld copy is not a network
    loss. The censor's own tx accounting keeps the queue slot, modeling a
    lying node that claims to forward."""
    return attacker[:, None] & (conns >= 0)


def eclipse_setup(
    state: SimState, conns: jnp.ndarray, attacker: jnp.ndarray, publisher: int
) -> SimState:
    """Overwrite the publisher's mesh row with its attacker edges only —
    the moment the eclipse closes (every slot the victim meshes through is
    a sybil). The attacker rows keep/gain the reciprocal edges through the
    graft-flood behavior; honest recovery happens through the normal
    heartbeat (graft fills the row back when scoring empties it)."""
    # only the publisher's row is touched: gather its neighbors directly
    # (nbr_is_attacker[i] = attacker[conns[pub, i]]) instead of a full pull
    row = jnp.where(conns[publisher] >= 0,
                    attacker[jnp.clip(conns[publisher], 0)], False)
    mesh = state.mesh_mask.at[publisher].set(row)
    return state.replace(mesh_mask=mesh)


@partial(jax.jit, static_argnames=("params", "adv", "batch_factor"))
def adversary_round(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    batch_factor: int = 1,
    nbr_ok: jnp.ndarray | None = None,
    edge_ok: jnp.ndarray | None = None,
    hb_idx: jnp.ndarray | None = None,
):
    """One heartbeat of attacker behavior + honest defense accounting,
    applied AFTER heartbeat_step. Returns (new_state, obs) where obs holds
    the per-round scalar observables the campaign's engagement/recovery
    metrics are built from. All ops are fixed-shape masked array passes.

    `edge_ok`: the same per-edge availability mask heartbeat_step takes
    (ops/faults.py) — a partitioned edge carries no attack traffic either.
    `hb_idx`: the scan's 0-based round index; required (traced, from the
    scan xs) when adv.identity_rotation so the scrub cadence is part of the
    compiled program, ignored otherwise."""
    if adv.identity_rotation and hb_idx is None:
        raise ValueError("identity_rotation needs the scan round index "
                         "(hb_idx) to schedule the identity scrubs")
    t = state.t_ms
    if nbr_ok is None:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)
    valid = ((conns >= 0) & state.alive[:, None] & nbr_ok
             & state.subscribed[:, None])
    if edge_ok is not None:
        valid = valid & edge_ok
    att_row = attacker[:, None] & valid   # attacker out-edges
    honest = ~attacker & state.alive & state.subscribed

    mesh = state.mesh_mask
    slow_penalty = state.slow_penalty
    uplink_free_ms = state.uplink_free_ms
    backoff_until = state.backoff_until
    fmd = state.fmd
    grafts, grafts_rx = state.grafts, state.grafts_rx
    ihave_tx, ihave_rx = state.ihave_tx, state.ihave_rx
    iwant_tx, iwant_rx = state.iwant_tx, state.iwant_rx

    if adv.identity_rotation:
        # rotation round: every edge incident to an attacker carries "a new
        # peer on the same socket slot" — the honest side's per-edge memory
        # of the old identity (mesh membership, delivery credit, penalty
        # counter, backoff) resets, and so does the attacker's own row.
        # Under a lax.cond: off-cadence rounds pay a scalar probe only.
        def _scrub(m, sl, f, b):
            inc = (attacker[:, None] | neighbor_pull_bool(
                attacker, conns, rev, batch_factor)) & (conns >= 0)
            return (m & ~inc, jnp.where(inc, 0.0, sl),
                    jnp.where(inc, 0.0, f), jnp.where(inc, 0.0, b))

        rot = (hb_idx % adv.rotation_period_hb) == (adv.rotation_period_hb - 1)
        mesh, slow_penalty, fmd, backoff_until = jax.lax.cond(
            rot, _scrub, lambda m, sl, f, b: (m, sl, f, b),
            mesh, slow_penalty, fmd, backoff_until)

    if adv.graft_flood:
        # the attacker GRAFTs every valid edge, every heartbeat, ignoring
        # backoff. The receive side is one reciprocal pull; v1.1 handleGraft
        # accepts a first graft (no backoff, grafter not negatively scored)
        # and treats a re-GRAFT of a backed-off or already-meshed edge as
        # the graft-flood violation (go-libp2p-pubsub adds a behaviour
        # penalty for exactly this).
        flood = att_row
        rx = reciprocal_pull_bool(flood, conns, rev, batch_factor)
        violation = rx & ((backoff_until > t) | mesh)
        # rotation reads the POST-scrub counters (a fresh identity is
        # accepted); every other scenario reads state.* untouched, keeping
        # those traces bit-identical to the pre-rotation engine
        sc = (state.replace(fmd=fmd, slow_penalty=slow_penalty).score(params)
              if adv.identity_rotation else state.score(params))
        accept = rx & ~violation & (sc >= 0.0)
        mesh = (mesh | flood | accept) & valid
        slow_penalty = slow_penalty + jnp.where(
            violation, jnp.float32(adv.violation_penalty), 0.0)
        grafts = grafts + flood.sum(axis=-1, dtype=jnp.int32)
        grafts_rx = grafts_rx + rx.sum(axis=-1, dtype=jnp.int32)

    if adv.ihave_spam:
        # bogus IHAVEs on every valid attacker edge; honest receivers IWANT
        # each unseen id and the answer never comes — the broken-promise
        # violation accrues once per spammed edge per heartbeat (the v1.1
        # IWANT-timeout behaviour penalty, applied at the round grain)
        ann = att_row
        rx_ann = reciprocal_pull_bool(ann, conns, rev, batch_factor)
        k = jnp.int32(adv.spam_ihaves_per_hb)
        ihave_tx = ihave_tx + ann.sum(axis=-1, dtype=jnp.int32) * k
        ihave_rx = ihave_rx + rx_ann.sum(axis=-1, dtype=jnp.int32) * k
        # IWANT flows back along the same involution: honest tx, attacker rx
        iwant_tx = iwant_tx + rx_ann.sum(axis=-1, dtype=jnp.int32) * k
        iwant_rx = iwant_rx + ann.sum(axis=-1, dtype=jnp.int32) * k
        slow_penalty = slow_penalty + jnp.where(
            rx_ann, jnp.float32(adv.violation_penalty), 0.0)

    if adv.iwant_spam:
        # unsolicited IWANT requests on every valid attacker edge. The
        # honest side answers requests from edges it has not graylisted yet
        # (scored on the PRE-round counter: the refusal reacts one round
        # late, like a real score cache), and every answered id serializes
        # `iwant_answer_ms` onto the victim's shared uplink — the
        # amplification: requests are tiny, answers are messages. The
        # unsolicited request itself is the violation (penalty per spammed
        # edge per heartbeat), so scoring caps the damage.
        req = att_row
        rx_req = reciprocal_pull_bool(req, conns, rev, batch_factor)
        k = jnp.int32(adv.spam_iwants_per_hb)
        sc0 = state.score(params)
        serve = rx_req & (sc0 >= params.graylist_threshold)
        served = serve.sum(axis=-1, dtype=jnp.int32) * k   # answers sent
        iwant_tx = iwant_tx + req.sum(axis=-1, dtype=jnp.int32) * k
        iwant_rx = iwant_rx + rx_req.sum(axis=-1, dtype=jnp.int32) * k
        uplink_free_ms = jnp.where(
            served > 0,
            jnp.maximum(uplink_free_ms, t)
            + served.astype(jnp.float32) * jnp.float32(adv.iwant_answer_ms),
            uplink_free_ms)
        slow_penalty = slow_penalty + jnp.where(
            rx_req, jnp.float32(adv.violation_penalty), 0.0)

    if adv.slow_mimicry and params.slow_weight < 0.0:
        # the attacker meters its own misbehavior so the penalty counter on
        # every edge viewing an attacker sits at mimic_margin * c_req: the
        # attacker's score in the honest peer's view is mimic_margin *
        # graylist_threshold — below the gossip/publish thresholds (it is
        # never gossiped to and is skipped at publish) yet above the
        # graylist and eviction floors, so it is never refused, never
        # evicted. Re-pinned every heartbeat: decay and the post-publish
        # censorship penalty are both clamped back onto the pin.
        c_req = params.graylist_threshold / params.slow_weight
        att_view = neighbor_pull_bool(attacker, conns, rev, batch_factor)
        slow_penalty = jnp.where(
            valid & att_view,
            jnp.float32(adv.mimic_margin * c_req), slow_penalty)

    rotation_extra = {}
    if adv.identity_rotation:
        # the scrub is the only writer of these two leaves; keeping them
        # out of the replace on every other scenario keeps those traces
        # bit-identical to the pre-rotation engine
        rotation_extra = dict(fmd=fmd, backoff_until=backoff_until)
    new_state = state.replace(
        mesh_mask=mesh, slow_penalty=slow_penalty,
        uplink_free_ms=uplink_free_ms,
        grafts=grafts, grafts_rx=grafts_rx,
        ihave_tx=ihave_tx, ihave_rx=ihave_rx,
        iwant_tx=iwant_tx, iwant_rx=iwant_rx,
        **rotation_extra,
    )

    obs = attack_observables(new_state, conns, rev, attacker, params,
                             batch_factor=batch_factor, valid=valid)
    return new_state, obs


def attack_observables(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    batch_factor: int = 1,
    valid: jnp.ndarray | None = None,
):
    """The per-round scalar observables the campaign's engagement/recovery
    metrics are built from (the scan stacks them into (steps,) curves).
    Shared by adversary_round and the recovery runner (ops/repair.py) so
    attack-window and recovery-window curves concatenate seamlessly."""
    if valid is None:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)
        valid = ((conns >= 0) & state.alive[:, None] & nbr_ok
                 & state.subscribed[:, None])
    honest = ~attacker & state.alive & state.subscribed
    mesh = state.mesh_mask
    sc = state.score(params)
    att_nbr = neighbor_pull_bool(attacker, conns, rev, batch_factor)
    h_att_edge = valid & att_nbr & honest[:, None]   # honest view of attackers
    n_e = jnp.maximum(h_att_edge.sum(), 1)
    f32 = jnp.float32
    return {
        # fraction of honest->attacker edges the receiver graylists
        "graylisted_frac": (h_att_edge
                            & (sc < params.graylist_threshold)).sum() / f32(n_e),
        "attacker_score_mean": jnp.where(h_att_edge, sc, 0.0).sum() / f32(n_e),
        # attacker share of honest peers' mesh edges (mesh recovery metric)
        "attacker_mesh_share": (
            (mesh & att_nbr & honest[:, None]).sum()
            / f32(jnp.maximum((mesh & honest[:, None]).sum(), 1))),
        "honest_mean_degree": (
            (mesh & honest[:, None]).sum()
            / f32(jnp.maximum(honest.sum(), 1))),
    }


@partial(jax.jit, static_argnames=("params", "adv", "batch_factor"))
def adaptive_round(
    state: SimState,
    ctrl: AdaptiveCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    batch_factor: int = 1,
    nbr_ok: jnp.ndarray | None = None,
    edge_ok: jnp.ndarray | None = None,
    hb_idx: jnp.ndarray | None = None,
    att_sorted: jnp.ndarray | None = None,
    n_att: jnp.ndarray | None = None,
):
    """One heartbeat of the ADAPTIVE attacker controller + honest defense
    accounting, applied AFTER heartbeat_step (and after repair_round in the
    recovery runner). The armed sibling of adversary_round: same masked
    fixed-shape algebra, zero PRNG, but the attacker's round behavior is a
    function of the controller carry `ctrl` instead of a constant mask.
    Returns ((new_state, new_ctrl), obs); obs carries attack_observables
    plus the adv_* controller channels (ops/telemetry.py).

    `hb_idx`: the scan's 0-based round index (rotates the sybil-id schedule
    of the PX poisoner); `att_sorted`/`n_att` are the scan-invariant sorted
    cohort ids / cohort size the runners hoist (recomputed here when absent
    so the round stays callable standalone).

    State-machine per attacker row, per round:

      1. PREDICT: next-round counter estimate = viol_est * slow_decay +
         violation_penalty (what one more flood round would cost).
      2. ACT or THROTTLE (duty_cycle): flood every valid edge iff the
         prediction stays under throttle_margin * c_req; otherwise send
         only LEGAL grafts this round (backoff expired, edge not meshed —
         the regraft behavior, which accrues nothing).
      3. OBSERVE: update viol_est from the attacker's OWN tx view — an
         edge it grafted while its own backoff/mesh bits were set violated
         on the honest side too (backoff writes are reciprocal everywhere
         in the engine; the attacker's mesh bit over-approximates the
         honest one since the flood sets it unilaterally, so the estimate
         is conservative and the margin covers residual asymmetry).
      4. POISON (px_poison, pool leaves live): plant px_poison_per_hb sybil
         ids into the px_pool row of every honest peer adjacent to the
         cohort, filling empty (-1) slots only — the same write discipline
         as heartbeat's PX capture, consumed by repair_round's candidate
         lattice. With repair fully inert the leaves are stripped and this
         block compiles out (pool is None)."""
    pol = adv.adaptive
    if not pol.enabled:
        raise ValueError("adaptive_round requires an armed AdaptivePolicy; "
                         "the disabled path is run_attacked_heartbeats")
    f32, i32 = jnp.float32, jnp.int32
    t = state.t_ms
    if nbr_ok is None:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)
    valid = ((conns >= 0) & state.alive[:, None] & nbr_ok
             & state.subscribed[:, None])
    if edge_ok is not None:
        valid = valid & edge_ok
    att_row = attacker[:, None] & valid
    n = conns.shape[0]
    me = jnp.arange(n, dtype=i32)

    # -- 1/2: score-aware duty cycle ------------------------------------
    if pol.duty_cycle and params.slow_weight < 0.0:
        c_req = f32(params.graylist_threshold / params.slow_weight)
        predicted = ctrl.viol_est * f32(params.slow_decay) \
            + f32(adv.violation_penalty)
        act = attacker & (predicted < f32(pol.throttle_margin) * c_req)
    else:
        act = attacker

    # -- graft set: full flood when acting, legal-only when throttled ----
    legal = att_row & (state.backoff_until <= t) & ~state.mesh_mask
    graft = att_row & act[:, None]
    if pol.regraft:
        graft = graft | legal
    rx = reciprocal_pull_bool(graft, conns, rev, batch_factor)
    violation = rx & ((state.backoff_until > t) | state.mesh_mask)
    sc = state.score(params)
    accept = rx & ~violation & (sc >= 0.0)
    mesh = (state.mesh_mask | graft | accept) & valid
    slow_penalty = state.slow_penalty + jnp.where(
        violation, f32(adv.violation_penalty), 0.0)
    grafts = state.grafts + graft.sum(axis=-1, dtype=i32)
    grafts_rx = state.grafts_rx + rx.sum(axis=-1, dtype=i32)

    # -- 3: controller estimate update (the attacker's own tx view) -----
    self_viol = (graft & ((state.backoff_until > t)
                          | state.mesh_mask)).any(axis=-1)
    viol_est = ctrl.viol_est * f32(params.slow_decay) + jnp.where(
        attacker & self_viol, f32(adv.violation_penalty), 0.0)
    regrafts = ctrl.regrafts
    if pol.regraft:
        regrafts = regrafts + jnp.where(
            attacker, legal.sum(axis=-1, dtype=i32), 0)
    throttled_hb = ctrl.throttled_hb + (attacker & ~act).astype(i32)

    # -- 4: PX poisoning (sybil answers to PX demand) --------------------
    px_injected = ctrl.px_injected
    pool = state.px_pool
    extra = {}
    if pol.px_poison and pool is not None:
        if att_sorted is None:
            att_sorted = jnp.sort(jnp.where(attacker, me, i32(n)))
        if n_att is None:
            n_att = attacker.sum()
        att_nbr = neighbor_pull_bool(attacker, conns, rev, batch_factor)
        victim = (~attacker & state.alive & state.subscribed
                  & (att_nbr & valid).any(axis=-1))
        hb = hb_idx if hb_idx is not None else 0
        base = me + hb * i32(pol.px_poison_per_hb)
        denom = jnp.maximum(n_att, 1)
        for k in range(pol.px_poison_per_hb):
            cand = att_sorted[(base + k) % denom]
            empty = pool < 0
            slot = jnp.argmax(empty, axis=-1)
            do = victim & (n_att > 0) & (cand < n) & empty.any(axis=-1)
            pool = pool.at[me, slot].set(
                jnp.where(do, cand, pool[me, slot]))
            px_injected = px_injected + do.astype(i32)
        extra["px_pool"] = pool

    new_state = state.replace(
        mesh_mask=mesh, slow_penalty=slow_penalty,
        grafts=grafts, grafts_rx=grafts_rx, **extra)
    new_ctrl = AdaptiveCtrl(viol_est=viol_est, regrafts=regrafts,
                            px_injected=px_injected,
                            throttled_hb=throttled_hb)

    from .telemetry import adaptive_observables

    obs = attack_observables(new_state, conns, rev, attacker, params,
                             batch_factor=batch_factor, valid=valid)
    obs.update(adaptive_observables(
        new_state, new_ctrl, attacker,
        acting=act, violations=violation.sum(dtype=i32)))
    return (new_state, new_ctrl), obs


def run_attacked_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    """lax.scan of [heartbeat_step -> adversary_round] x steps.

    Unlike run_heartbeats, decay is NOT deferred to scan end and the
    carried-degree protocol is off: adversary_round writes the penalty
    counter and the mesh mid-scan, so per-round decay interleaving and the
    per-step mesh&valid AND are both load-bearing. The alive/subscribed
    neighbor pull still hoists when churn is off (the attack mutates
    neither). Returns (state, obs) with obs leaves shaped (steps,).

    Like run_heartbeats, the jit boundary is the inner function: no attack
    behavior touches the mesh-repair leaves, so attack windows with repair
    off (the common campaign case — repair arms only the RECOVERY window)
    run with the 5 repair leaves stripped from the scan carry.

    `telemetry`: optional armed ops/telemetry.TelemetryParams — the flight
    recorder's per-round tel_* channels join the obs dict. None or a
    disabled params normalizes to None and takes the IDENTICAL python
    trace path (same jaxpr, same jit cache entry as the pre-recorder
    engine); armed telemetry consumes no PRNG and writes no state leaf,
    so the protocol trajectory is bit-identical either way."""
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if repair_inert(params):
        state, saved = strip_repair(state)
        out, obs = _run_attacked_heartbeats(
            state, conns, rev, out_mask, attacker, params, adv, steps,
            batch_factor, telemetry)
        return restore_repair(out, saved), obs
    return _run_attacked_heartbeats(
        state, conns, rev, out_mask, attacker, params, adv, steps,
        batch_factor, telemetry)


@partial(jax.jit, static_argnames=("params", "adv", "steps", "batch_factor",
                                   "telemetry"))
def _run_attacked_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    nbr_ok = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    # identity rotation needs the round index inside the compiled body (the
    # scrub cadence); every other scenario scans over nothing, as before
    xs = jnp.arange(steps) if adv.identity_rotation else None

    def body(s, hb):
        s = heartbeat_step(s, conns, rev, out_mask, params,
                           batch_factor=batch_factor, nbr_ok=nbr_ok)
        s, obs = adversary_round(s, conns, rev, attacker, params, adv,
                                 batch_factor=batch_factor, nbr_ok=nbr_ok,
                                 hb_idx=hb)
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        return s, obs

    return jax.lax.scan(body, state, xs, length=steps)


def run_adaptive_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    steps: int,
    ctrl: AdaptiveCtrl | None = None,
    batch_factor: int = 1,
    telemetry=None,
):
    """The adaptive attack window: lax.scan of [heartbeat_step ->
    adaptive_round] x steps with the per-attacker controller carry.

    Disabled (`not adv.adaptive.enabled`) this IS run_attacked_heartbeats —
    the same call, the same jit cache entry, bit-identical, zero extra PRNG
    (the faults/telemetry/DHT delegation pattern); `ctrl` must be None and
    the return is the base runner's (state, obs). Armed, `ctrl` defaults to
    a fresh init_adaptive_ctrl(params.n) and the return widens to
    ((state, ctrl), obs) — the run_dht_recovery_heartbeats carry
    convention. Armed obs adds the adv_* controller channels; with repair
    fully inert the 5 repair leaves are still stripped around the jit (the
    PX poisoner compiles out: nothing could read the pool)."""
    if not adv.adaptive.enabled:
        if ctrl is not None:
            raise ValueError("ctrl given but adv.adaptive is disabled — the "
                             "disabled path delegates to "
                             "run_attacked_heartbeats and carries none")
        return run_attacked_heartbeats(
            state, conns, rev, out_mask, attacker, params, adv, steps,
            batch_factor, telemetry)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if ctrl is None:
        ctrl = init_adaptive_ctrl(params.n)
    if repair_inert(params):
        state, saved = strip_repair(state)
        (out, ctrl), obs = _run_adaptive_heartbeats(
            state, ctrl, conns, rev, out_mask, attacker, params, adv, steps,
            batch_factor, telemetry)
        return (restore_repair(out, saved), ctrl), obs
    return _run_adaptive_heartbeats(
        state, ctrl, conns, rev, out_mask, attacker, params, adv, steps,
        batch_factor, telemetry)


@partial(jax.jit, static_argnames=("params", "adv", "steps", "batch_factor",
                                   "telemetry"))
def _run_adaptive_heartbeats(
    state: SimState,
    ctrl: AdaptiveCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    nbr_ok = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    # the PX poisoner's sybil-id schedule is scan-invariant: hoist it
    n = conns.shape[0]
    att_sorted = jnp.sort(jnp.where(
        attacker, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)))
    n_att = attacker.sum()

    def body(carry, hb):
        s, c = carry
        s = heartbeat_step(s, conns, rev, out_mask, params,
                           batch_factor=batch_factor, nbr_ok=nbr_ok)
        (s, c), obs = adaptive_round(
            s, c, conns, rev, attacker, params, adv,
            batch_factor=batch_factor, nbr_ok=nbr_ok, hb_idx=hb,
            att_sorted=att_sorted, n_att=n_att)
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        return (s, c), obs

    return jax.lax.scan(body, (state, ctrl), jnp.arange(steps), length=steps)


def censorship_penalty_update(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    attacker: jnp.ndarray,
    received: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
) -> SimState:
    """Post-publish P3 analog (mesh message delivery failures): a receiver
    that obtained the message penalizes mesh members that silently delivered
    none of it. The engine's score subset has no per-edge delivery-window
    bookkeeping, so the deficit edge set is computed from the adversary
    ground truth (mesh edges toward censoring attackers) — the EFFECT of P3
    at the round grain, documented as such in docs/ARCHITECTURE.md."""
    if float(adv.censor_penalty) == 0.0:
        return state
    att_nbr = neighbor_pull_bool(attacker, conns, rev)
    deficit = (state.mesh_mask & att_nbr
               & (received & ~attacker)[:, None])
    return state.replace(slow_penalty=state.slow_penalty + jnp.where(
        deficit, jnp.float32(adv.censor_penalty), 0.0))
