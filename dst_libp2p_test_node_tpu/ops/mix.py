"""Mix-routing (anonymity relay) layer — the MOUNTSMIX/USESMIX surface.

The reference README documents a mix protocol for nim-libp2p nodes
(README.md:30,42-46: MOUNTSMIX, USESMIX, NUMMIX, MIXD, FILEPATH) whose
implementation is absent from the snapshot (SURVEY.md §5: only the parsed
`filePath` remains, gossipsub-queues/env.nim:22). BASELINE config 5
("1M-peer mix-routed, MOUNTSMIX, MIXD=4") requires it, so this module
implements the documented semantics from first principles:

  a publisher that *uses* the mix network (USESMIX) does not publish
  directly; it wraps the message in MIXD layers (Sphinx-style onion) and
  sends it through MIXD distinct mix nodes drawn from the NUMMIX peers that
  *mount* the protocol (MOUNTSMIX). The final mix node — the exit — injects
  the message into GossipSub. Receivers still measure latency against the
  timestamp the *origin* embedded, so the mix path delay (per-hop link
  latency + uplink serialization of the padded packet + per-hop unwrap
  processing) is part of the measured dissemination latency.

TPU shape: path sampling is a masked top-k over one uniform draw (no
Python loops, no rejection sampling); per-hop delays are two gathers into
the stage-latency matrix; everything jits and vmaps over simultaneous
publishes. Mix-node assignment is deterministic from peer ordinals
(ids [0, NUMMIX)), mirroring the reference's hostname-ordinal role
convention (kad-dht/env.nim:27-28 assigns roles by ordinal the same way).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

# Sphinx packets are fixed-size regardless of payload (that is the point of
# the format: unlinkability). 2413 B is the classic Sphinx packet size used
# by mixnet implementations; messages larger than the packet body would
# fragment, which we model as ceil(payload / body) serialized packets.
SPHINX_PACKET_BYTES = 2413
SPHINX_BODY_BYTES = 2048


@dataclass(frozen=True)
class MixParams:
    """Static mix-network parameters (hashable -> jit static arg)."""

    num_mix: int            # NUMMIX — peers [0, num_mix) mount the protocol
    mix_d: int = 4          # MIXD — hops to traverse
    proc_delay_ms: float = 5.0   # per-hop Sphinx unwrap + re-forward cost
    packet_bytes: int = SPHINX_PACKET_BYTES
    body_bytes: int = SPHINX_BODY_BYTES

    def validate(self) -> None:
        if self.mix_d < 1:
            raise ValueError("MIXD must be >= 1")
        if self.num_mix < self.mix_d:
            raise ValueError(
                f"need NUMMIX >= MIXD distinct mix nodes, got "
                f"{self.num_mix} < {self.mix_d}"
            )


def mix_node_mask(n: int, num_mix: int) -> jnp.ndarray:
    """(N,) bool — which peers mount the mix protocol (ordinal rule)."""
    return jnp.arange(n) < num_mix


def eligible_mix_count(alive, publisher: int, n: int, num_mix: int) -> int:
    """How many mix nodes can actually relay for this publisher right now
    (mounted, alive, and not the publisher itself). Callers must check this
    is >= mix_d before mix_route — the jitted sampler cannot raise."""
    import numpy as np

    m = np.asarray(mix_node_mask(n, num_mix)) & np.asarray(alive)
    if publisher < num_mix:
        m = m.copy()
        m[publisher] = False
    return int(m.sum())


@partial(jax.jit, static_argnames=("params", "n"))
def mix_route(
    key: jnp.ndarray,
    publisher,
    alive: jnp.ndarray,          # (N,) bool churn mask
    stage: jnp.ndarray,          # (N,) int32 topology stage per peer
    lat_ms: jnp.ndarray,         # (S, S) stage-pair latency
    bw_up_mbit_per_stage: jnp.ndarray,  # (S,)
    params: MixParams,
    n: int,
    payload_bytes,
    uplink_free_ms=None,         # (N,) or None: shared-uplink occupancy
    rx_free_ms=None,             # (N,) or None: shared-downlink occupancy
    t0_ms=0.0,                   # absolute origin send time (occupancy mode)
):
    """Sample a MIXD-hop path and price it.

    Returns (path, exit_node, path_delay_ms) — the MIXD relay peer ids, the
    peer that will publish into GossipSub on the origin's behalf, and the
    elapsed time between the origin's send and the exit node being ready to
    publish — plus, when occupancy arrays are given,
    (uplink_free_new, rx_free_new).

    Dead mix nodes (churn) are excluded from the draw; the publisher never
    relays its own packet. Sampling MIXD distinct nodes = top-MIXD of one
    uniform vector masked to eligible mix nodes — an argsort, not a loop.
    Precondition (host-checked via eligible_mix_count): at least mix_d
    eligible nodes, else the path tail would silently pick up ineligible
    peers.

    Occupancy coupling (mix and GossipSub traffic share each node's real
    links): with `uplink_free_ms`/`rx_free_ms`, every hop's serialization
    starts no earlier than the sender's uplink drains in-flight mesh/gossip
    traffic (start = max(ready, uplink_free[sender])), the arriving packets
    drain the relay's downlink behind earlier arrivals (completion =
    max(wire, rx_free[relay] + rx_ms)), and both occupancies are written
    back — so a mix relay's subsequent mesh forwarding queues behind the
    Sphinx transmission it just made, and vice versa. Hops are chained
    sequentially (the packet exists at one relay at a time), mix_d is
    static, so the loop unrolls into straight-line XLA.
    """
    mix_ok = mix_node_mask(n, params.num_mix) & alive
    mix_ok = mix_ok & (jnp.arange(n) != publisher)
    u = jax.random.uniform(key, (n,))
    # ineligible nodes sort last; caller guarantees >= mix_d eligible
    order = jnp.argsort(jnp.where(mix_ok, u, 2.0))
    path = order[: params.mix_d]                        # (MIXD,) peer ids

    # hop endpoints: origin -> m1 -> ... -> m_MIXD (exit)
    hops_from = jnp.concatenate([jnp.asarray([publisher]), path[:-1]])
    hops_to = path
    hop_lat = lat_ms[stage[hops_from], stage[hops_to]]  # (MIXD,)

    # each hop serializes ceil(payload/body) fixed-size packets on the
    # sender's uplink, then pays the unwrap cost at the receiver.
    # payload_bytes stays a traced value: /publish takes msgSize per request
    # (runtime/node_service.py), so baking it static would recompile the
    # publish hot path for every distinct size
    n_packets = jnp.ceil(jnp.asarray(payload_bytes, jnp.float32) / params.body_bytes)
    wire_bytes = n_packets * params.packet_bytes
    tx_ms = (wire_bytes * 8.0) / (bw_up_mbit_per_stage[stage[hops_from]] * 1e6) * 1e3
    if uplink_free_ms is None:
        delay = jnp.sum(hop_lat + tx_ms) + params.mix_d * params.proc_delay_ms
        return path, path[-1], delay.astype(jnp.float32)

    # occupancy-coupled chain: absolute times, hop by hop
    uplink = jnp.asarray(uplink_free_ms, jnp.float32)
    rx_free = (jnp.zeros((n,), jnp.float32) if rx_free_ms is None
               else jnp.asarray(rx_free_ms, jnp.float32))
    # reference topology: bw_down == bw_up per stage (shadow/topogen.py:50-51)
    rx_hop = (wire_bytes * 8.0) / (
        bw_up_mbit_per_stage[stage[hops_to]] * 1e6) * 1e3
    ready = jnp.asarray(t0_ms, jnp.float32)
    for h in range(params.mix_d):
        s, r = hops_from[h], hops_to[h]
        start = jnp.maximum(ready, uplink[s])
        uplink = uplink.at[s].set(start + tx_ms[h])
        wire = start + tx_ms[h] + hop_lat[h]
        done = jnp.maximum(wire, rx_free[r] + rx_hop[h])
        rx_free = rx_free.at[r].set(done)
        ready = done + params.proc_delay_ms   # Sphinx unwrap at the relay
    delay = (ready - t0_ms).astype(jnp.float32)
    return path, path[-1], delay, uplink, rx_free


def mix_wire_bytes(params: MixParams, payload_bytes: int) -> int:
    """Bytes each mix hop puts on the wire for one message (padding incl.)."""
    n_packets = -(-payload_bytes // params.body_bytes)
    return n_packets * params.packet_bytes
