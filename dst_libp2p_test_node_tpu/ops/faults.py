"""Fault injection: node churn, partitions, and latency spikes as masks.

The reference harness measures GossipSub under adversity the network
inflicts, not just adversaries: Shadow injects latency/loss, nodes crash and
return, links die in bulk (SURVEY §5; the v1.1 evaluation arXiv:2007.02754
treats churn and partition-heal as first-class resilience scenarios). This
module compiles that fault model into the SAME scan the attack campaigns
already run — every fault is a scheduled mask over the existing fixed-shape
algebra, so "eclipse during a partition" is one config, not a new engine.

Three fault families, each a [start, end) window in heartbeat rounds
relative to the fault-armed scan:

  crash/restart   the cohort goes dark at crash_window[0] (alive=False: its
                  rows and its neighbors' views fall out of the validity
                  mask, exactly like BASELINE-config-4 churn) and returns at
                  crash_window[1] COLD — mesh membership, per-edge delivery
                  credit, penalty counters and backoffs are scrubbed on
                  every edge incident to a restarted peer, both directions
                  (a process restart forgets protocol state; its neighbors
                  re-handshake a fresh peer). The returned peer re-enters
                  through the normal graft path — and, when armed, the PR-4
                  repair path (PX/re-dial) — which is what
                  `post_churn_reconvergence_hb` measures.
  partition/heal  a node cut: `side` 2-colors the peers and every
                  cross-color edge is masked out of validity
                  (partition_edge_mask -> heartbeat_step/adversary_round
                  `edge_ok`) for the window. MESH MEMORY survives the
                  window: a partition is network-layer unreachability, not
                  a DISCONNECT — real GossipSub has no liveness-based mesh
                  eviction, so both endpoints still list the edge when the
                  link returns. The scan freezes the cross mesh edges at
                  partition start (heartbeat's mesh&valid would scrub them)
                  and thaws the still-valid ones at heal; the post-heal
                  rebalance (degrees exceed D_high: each side grafted
                  replacements during the cut) is the measured heal
                  transient (`heal_time_ms`, cross_mesh_edges curve).
  latency spike   the spiked cohort's uplink clock (SimState.uplink_free_ms
                  — the carry the dissemination fixpoint serializes
                  publishes through) is pushed `spike_ms` forward each
                  window round: the Shadow latency-injection analog, felt
                  as delivery delay by everything downstream.

Determinism contract (the strip_repair discipline from PR 5, applied at the
config level): `FaultParams()` is all-off, `run_faulted_heartbeats` then
literally delegates to run_attacked_heartbeats — same function object, same
jit cache entry, bit-identical outputs, zero PRNG consumed by any fault
(cohorts are drawn host-side in fault_masks; the armed scan adds no
jax.random call, so the key schedule equals the un-faulted run's).
tests/test_faults.py pins all three claims.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .adversary import (AdversaryParams, adaptive_round, adversary_round,
                        run_adaptive_heartbeats, run_attacked_heartbeats)
from .heartbeat import heartbeat_step
from .pull import neighbor_pull_bool
from .state import (SimParams, SimState, init_adaptive_ctrl, repair_inert,
                    restore_repair, strip_repair)

INF = jnp.float32(3.4e38)


@dataclass(frozen=True)
class FaultParams:
    """Static (hashable -> jit static arg) fault schedule. All windows are
    [start, end) in heartbeat rounds of the fault-armed scan; a family is
    armed iff its fraction is > 0 AND its window is non-empty. Defaults are
    all OFF — the disabled path is a pure delegation to the un-faulted
    runner (RepairParams' contract, ops/repair.py)."""

    crash_frac: float = 0.0
    crash_window: tuple[int, int] = (0, 0)
    partition_frac: float = 0.0
    partition_window: tuple[int, int] = (0, 0)
    spike_frac: float = 0.0
    spike_window: tuple[int, int] = (0, 0)
    spike_ms: float = 0.0

    @property
    def crash(self) -> bool:
        return self.crash_frac > 0.0 and self.crash_window[1] > self.crash_window[0]

    @property
    def partition(self) -> bool:
        return (self.partition_frac > 0.0
                and self.partition_window[1] > self.partition_window[0])

    @property
    def spike(self) -> bool:
        return (self.spike_frac > 0.0 and self.spike_ms > 0.0
                and self.spike_window[1] > self.spike_window[0])

    @property
    def enabled(self) -> bool:
        return self.crash or self.partition or self.spike

    def validate(self) -> None:
        for name, frac in (("crash_frac", self.crash_frac),
                           ("partition_frac", self.partition_frac),
                           ("spike_frac", self.spike_frac)):
            if not (0.0 <= frac < 1.0):
                raise ValueError(f"{name} must be in [0, 1), got {frac}")
        for name, win in (("crash_window", self.crash_window),
                          ("partition_window", self.partition_window),
                          ("spike_window", self.spike_window)):
            a, b = win
            if a < 0 or b < a:
                raise ValueError(
                    f"{name} must be [start, end) with 0 <= start <= end, "
                    f"got {win}")
        if self.spike_ms < 0.0:
            raise ValueError("spike_ms must be >= 0")


def fault_masks(
    n: int,
    faults: FaultParams,
    seed: int,
    publisher: int | None = None,
) -> dict[str, np.ndarray]:
    """Host-side TRIAL SETUP (attacker_cohort's contract): the per-trial
    fault cohorts as (N,) bool numpy arrays, deterministic in (seed,
    faults). Keys: 'crash' (restarting cohort — never the publisher, whose
    delivery the trial measures), 'side' (partition 2-coloring: True =
    side A, |A| = round(partition_frac * n)), 'spike' (latency-spiked
    cohort). Disabled families return all-False/zeros so the device
    signature never changes shape. NO device PRNG is consumed — this is
    the only randomness the fault subsystem ever draws."""
    crash = np.zeros(n, dtype=bool)
    side = np.zeros(n, dtype=bool)
    spike = np.zeros(n, dtype=bool)
    if faults.crash:
        k = int(round(faults.crash_frac * n))
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, 0xFA17, 0]))
        cand = np.arange(n)
        if publisher is not None:
            cand = cand[cand != publisher]
        k = min(k, len(cand))
        if k > 0:
            crash[rng.choice(cand, size=k, replace=False)] = True
    if faults.partition:
        k = int(round(faults.partition_frac * n))
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, 0xFA17, 1]))
        if k > 0:
            side[rng.choice(n, size=min(k, n), replace=False)] = True
    if faults.spike:
        k = int(round(faults.spike_frac * n))
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, 0xFA17, 2]))
        if k > 0:
            spike[rng.choice(n, size=min(k, n), replace=False)] = True
    return {"crash": crash, "side": side, "spike": spike}


def partition_edge_mask(side: jnp.ndarray, conns: jnp.ndarray) -> jnp.ndarray:
    """(N, C) bool: True on every connected edge that CROSSES the cut. The
    gather is row-owner -> neighbor color (side[conns[i, j]]), the same
    index economics as the involution pulls — side is (N,), so this is one
    embedding-style row gather, not a 2-index scatter."""
    return (conns >= 0) & (side[:, None] ^ side[jnp.clip(conns, 0)])


def run_faulted_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    faults: FaultParams,
    crash: jnp.ndarray,
    side: jnp.ndarray,
    spike: jnp.ndarray,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
    ctrl=None,
):
    """The fault-armed attack window: run_attacked_heartbeats with the
    fault schedule compiled into the scan body. `crash`/`side`/`spike` are
    the (N,) fault_masks cohorts as device arrays.

    Disabled (`not faults.enabled`) this IS run_attacked_heartbeats — the
    same call, the same jit cache entry — so the default path cannot drift
    from the un-faulted engine by construction (with an armed
    adv.adaptive the delegation target is run_adaptive_heartbeats, whose
    own disabled path closes the chain back to the base runner). Armed
    adaptive composes inside the faulted scan: the controller carry
    (`ctrl`, defaulting to a fresh init_adaptive_ctrl) threads through
    alongside the partition's frozen-edge bank, adaptive_round replaces
    adversary_round, and the return widens to ((state, ctrl), obs) — a
    crashed attacker's controller keeps its own estimate (the honest-side
    counters its restart scrubbed are forgotten by the HONEST peers, so
    the estimate stays conservative). Armed, the scan adds the
    per-family fault observables to the obs dict (present only when the
    family is armed; downstream reads use .get):

      cross_mesh_edges        (partition) mesh edges crossing the cut — 0
                              during the window, the heal signal after
      restarted_mean_degree   (crash) mean mesh degree over the restarting
                              cohort — 0 while dark, the reconvergence
                              signal after restart

    `telemetry`: optional armed ops/telemetry.TelemetryParams — the flight
    recorder's tel_* channels join the obs dict, same contract as
    run_attacked_heartbeats (disabled normalizes to None; identical trace).
    """
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if not faults.enabled:
        if adv.adaptive.enabled:
            return run_adaptive_heartbeats(
                state, conns, rev, out_mask, attacker, params, adv, steps,
                ctrl=ctrl, batch_factor=batch_factor, telemetry=telemetry)
        if ctrl is not None:
            raise ValueError("ctrl given but the adaptive policy is "
                             "disabled — the delegating path carries none")
        return run_attacked_heartbeats(
            state, conns, rev, out_mask, attacker, params, adv, steps,
            batch_factor, telemetry)
    if adv.adaptive.enabled and ctrl is None:
        ctrl = init_adaptive_ctrl(params.n)
    if not adv.adaptive.enabled and ctrl is not None:
        raise ValueError("ctrl given but the adaptive policy is disabled")
    if repair_inert(params):
        state, saved = strip_repair(state)
        out, obs = _run_faulted_heartbeats(
            state, conns, rev, out_mask, attacker, crash, side, spike,
            params, adv, faults, steps, batch_factor, telemetry, ctrl)
        if adv.adaptive.enabled:
            out, ctrl = out
            return (restore_repair(out, saved), ctrl), obs
        return restore_repair(out, saved), obs
    out, obs = _run_faulted_heartbeats(
        state, conns, rev, out_mask, attacker, crash, side, spike,
        params, adv, faults, steps, batch_factor, telemetry, ctrl)
    return out, obs


@partial(jax.jit,
         static_argnames=("params", "adv", "faults", "steps", "batch_factor",
                          "telemetry"))
def _run_faulted_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    crash: jnp.ndarray,
    side: jnp.ndarray,
    spike: jnp.ndarray,
    params: SimParams,
    adv: AdversaryParams,
    faults: FaultParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
    ctrl=None,
):
    adaptive = adv.adaptive.enabled
    if adaptive:
        # the PX poisoner's sybil-id schedule is scan-invariant: hoist it
        n_rows = conns.shape[0]
        att_sorted = jnp.sort(jnp.where(
            attacker, jnp.arange(n_rows, dtype=jnp.int32), jnp.int32(n_rows)))
        n_att = attacker.sum()
    nbr_ok = None
    if (not faults.crash and params.churn_down_per_hb == 0.0
            and params.churn_up_per_hb == 0.0):
        # liveness is scan-invariant without crash/churn: hoist the pull
        # (partition/spike never touch alive/subscribed — they mask edges
        # and clocks, so the hoist stays sound)
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    cross = partition_edge_mask(side, conns) if faults.partition else None
    crash_nbr = (neighbor_pull_bool(crash, conns, rev, batch_factor)
                 if faults.crash else None)

    def _go_dark(s):
        # the cohort's warm-start offsets were measured on the full liveness
        # set — invalidate the whole carry (heartbeat_step's churn contract)
        return s.replace(alive=s.alive & ~crash,
                         warm_offset_ms=jnp.full_like(s.warm_offset_ms, INF))

    def _restart(s):
        # cold return: every edge incident to a restarted peer forgets the
        # old session on BOTH sides — the peer must re-graft from nothing
        inc = (crash[:, None] | crash_nbr) & (conns >= 0)
        repl = dict(
            alive=s.alive | crash,
            mesh_mask=s.mesh_mask & ~inc,
            fmd=jnp.where(inc, 0.0, s.fmd),
            slow_penalty=jnp.where(inc, 0.0, s.slow_penalty),
            backoff_until=jnp.where(inc, 0.0, s.backoff_until),
            warm_offset_ms=jnp.full_like(s.warm_offset_ms, INF),
        )
        if not repair_inert(params):
            # repair leaves ride the carry only when a knob is armed; a
            # restarted peer's PX pool and starvation clock reset with it
            repl["px_pool"] = jnp.where(crash[:, None], -1, s.px_pool)
            repl["starve_hb"] = jnp.where(crash, 0, s.starve_hb)
        return s.replace(**repl)

    def _freeze(s, frozen):
        # partition start: pull the cross mesh edges out of the live mesh
        # (heartbeat's mesh&valid would scrub them permanently) and bank
        # them — mesh memory survives a network-layer cut
        return (s.replace(mesh_mask=s.mesh_mask & ~cross),
                s.mesh_mask & cross)

    def _thaw(s, frozen):
        # heal: restore the banked edges whose endpoints both still stand
        ok = s.alive & s.subscribed
        keep = frozen & ok[:, None] & ok[jnp.clip(conns, 0)]
        return (s.replace(mesh_mask=s.mesh_mask | keep),
                jnp.zeros_like(frozen))

    def body(carry, hb):
        frozen = c = None
        if faults.partition and adaptive:
            s, c, frozen = carry
        elif faults.partition:
            s, frozen = carry
        elif adaptive:
            s, c = carry
        else:
            s = carry
        if faults.crash:
            cs, ce = faults.crash_window
            s = jax.lax.cond(hb == cs, _go_dark, lambda x: x, s)
            s = jax.lax.cond(hb == ce, _restart, lambda x: x, s)
        edge_ok = None
        if faults.partition:
            ps, pe = faults.partition_window
            s, frozen = jax.lax.cond(
                hb == ps, _freeze, lambda a, b: (a, b), s, frozen)
            s, frozen = jax.lax.cond(
                hb == pe, _thaw, lambda a, b: (a, b), s, frozen)
            edge_ok = jnp.where((hb >= ps) & (hb < pe), ~cross, True)
        s = heartbeat_step(s, conns, rev, out_mask, params,
                           batch_factor=batch_factor, nbr_ok=nbr_ok,
                           edge_ok=edge_ok)
        if adaptive:
            (s, c), obs = adaptive_round(
                s, c, conns, rev, attacker, params, adv,
                batch_factor=batch_factor, nbr_ok=nbr_ok, edge_ok=edge_ok,
                hb_idx=hb, att_sorted=att_sorted, n_att=n_att)
        else:
            s, obs = adversary_round(s, conns, rev, attacker, params, adv,
                                     batch_factor=batch_factor, nbr_ok=nbr_ok,
                                     edge_ok=edge_ok, hb_idx=hb)
        if faults.spike:
            # push the spiked cohort's uplink clock forward: the next
            # publish serializes behind the spike, exactly like an
            # iwant-spam answer queue (ops/adversary.py)
            ss, se = faults.spike_window
            live = (hb >= ss) & (hb < se)
            s = s.replace(uplink_free_ms=jnp.where(
                spike & live,
                jnp.maximum(s.uplink_free_ms, s.t_ms)
                + jnp.float32(faults.spike_ms),
                s.uplink_free_ms))
        f32 = jnp.float32
        if faults.partition:
            obs["cross_mesh_edges"] = (s.mesh_mask & cross).sum().astype(f32)
        if faults.crash:
            obs["restarted_mean_degree"] = (
                (s.mesh_mask & crash[:, None]).sum()
                / f32(jnp.maximum(crash.sum(), 1)))
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        if faults.partition and adaptive:
            return (s, c, frozen), obs
        if faults.partition:
            return (s, frozen), obs
        if adaptive:
            return (s, c), obs
        return s, obs

    xs = jnp.arange(steps)
    if faults.partition and adaptive:
        carry0 = (state, ctrl, jnp.zeros_like(state.mesh_mask))
        (state, ctrl, _), obs = jax.lax.scan(body, carry0, xs, length=steps)
    elif faults.partition:
        carry0 = (state, jnp.zeros_like(state.mesh_mask))
        (state, _), obs = jax.lax.scan(body, carry0, xs, length=steps)
    elif adaptive:
        (state, ctrl), obs = jax.lax.scan(body, (state, ctrl), xs,
                                          length=steps)
    else:
        state, obs = jax.lax.scan(body, state, xs, length=steps)
    return ((state, ctrl) if adaptive else state), obs
