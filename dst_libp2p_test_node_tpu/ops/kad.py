"""Kademlia DHT substrate: XOR-metric routing tables and FIND_NODE lookups
as fixed-shape batched array ops.

The reference's kad-dht node (nim-test-node/kad-dht/{main,core,helpers}.nim)
delegates the protocol to nim-libp2p's KadDHT: a per-node routing table of
XOR-distance buckets, iterative FIND_NODE lookups (query the alpha closest
known peers, merge their k closest entries, repeat), and three roles —
RoleBootstrap (passive anchor), RoleNormal (warmup: 5x FIND_NODE(self) +
15x FIND_NODE(random), kad-dht/core.nim:12-35), RoleProbe (FIND_NODE(random)
every 5 s forever, core.nim:38-55). The regression node reuses the same
machinery for mesh discovery (regression/kad_utils.nim:81-94).

TPU-native design (not a port):
  keys[p]           (N, W) uint32 — 128-bit node key, host-generated per seed
  rtable[p]         (N, B, K) int32 — bucket b holds peers whose XOR distance
                    to p has bit-length KEY_BITS - b; -1 = empty slot
  find_node         vmapped iterative lookup: a lax.scan over lookup rounds,
                    each round queries ALPHA closest unqueried shortlist
                    entries in parallel (round time = max RTT, per the
                    iterative-lookup wait-for-all semantics), merges their
                    K_RESP closest entries via stable multi-word argsort.

Everything is a masked fixed-shape op: shortlists are padded to S entries,
bucket inserts route dropped entries out of bounds (`mode="drop"`), and
big-integer XOR comparisons are radix argsorts over the W key words — no
Python bigints, no dynamic shapes, so the whole lookup batch jits and shards
over the peer axis like the GossipSub engine.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

KEY_WORDS = 4                    # 128-bit keys; collisions ~ N^2 / 2^129
KEY_BITS = 32 * KEY_WORDS
ALPHA = 3                        # parallel queries per lookup round
K_RESP = 16                      # closest entries returned per FIND_NODE
PROC_MS = 2.0                    # per-query handler latency


def make_keys(n: int, seed: int = 0) -> np.ndarray:
    """Uniform 128-bit node keys, host-generated once per experiment (the
    reference derives keys from peer identities; only uniformity matters)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x6AD]))
    return rng.integers(0, 1 << 32, size=(n, KEY_WORDS), dtype=np.uint32)


def _bitlen32(x: jnp.ndarray) -> jnp.ndarray:
    """Bit length of each uint32 lane (0 for 0), via 5-step binary search."""
    x = x.astype(jnp.uint32)
    bl = jnp.zeros(x.shape, jnp.int32)
    for shift in (16, 8, 4, 2, 1):
        gt = x >= (jnp.uint32(1) << shift)
        bl = jnp.where(gt, bl + shift, bl)
        x = jnp.where(gt, x >> shift, x)
    return bl + (x > 0).astype(jnp.int32)


def xor_bitlen(d: jnp.ndarray) -> jnp.ndarray:
    """Bit length of the big-int whose words (most significant first) are the
    trailing axis. The first nonzero word strictly dominates, so a max over
    per-word contributions is exact."""
    w = jnp.arange(KEY_WORDS)
    contrib = (KEY_WORDS - 1 - w) * 32 + _bitlen32(d)
    return jnp.max(jnp.where(d > 0, contrib, 0), axis=-1).astype(jnp.int32)


def bucket_slot(d: jnp.ndarray, n_buckets: int) -> jnp.ndarray:
    """Bucket index for an XOR distance: 0 = farthest half of the keyspace.
    Distances closer than 2^(KEY_BITS - n_buckets) clamp into the last bucket
    (astronomically rare for uniform keys at any simulated N)."""
    return jnp.clip(KEY_BITS - xor_bitlen(d), 0, n_buckets - 1)


def lex_argsort(d: jnp.ndarray) -> jnp.ndarray:
    """Ascending big-int argsort over the trailing word axis of (..., M, W):
    repeated stable argsorts from least to most significant word (radix)."""
    idx = jnp.argsort(d[..., -1], axis=-1, stable=True)
    for w in range(KEY_WORDS - 2, -1, -1):
        key = jnp.take_along_axis(d[..., w], idx, axis=-1)
        refine = jnp.argsort(key, axis=-1, stable=True)
        idx = jnp.take_along_axis(idx, refine, axis=-1)
    return idx


def _dist(keys: jnp.ndarray, entries: jnp.ndarray, target_key: jnp.ndarray):
    """XOR distance of each entry to target; invalid entries (-1) -> max."""
    ek = keys[jnp.clip(entries, 0)]
    d = jnp.bitwise_xor(ek, target_key[..., None, :])
    return jnp.where((entries >= 0)[..., None], d, jnp.uint32(0xFFFFFFFF))


@struct.dataclass
class KadState:
    """Device-side DHT state (a jax pytree). keys are per-epoch constants but
    ride along so every op is self-contained.

    rt_fails/rt_retry_ms shadow the routing table slot-for-slot: the
    per-entry dial-failure count and the sim-ms deadline before the entry
    may be re-dialed (exponential backoff). Both stay all-zero unless
    `evict_failed` runs with a retry budget (max_fails > 1), so the default
    eviction path is unchanged."""

    rtable: jnp.ndarray      # (N, B, K) int32, -1 empty
    keys: jnp.ndarray        # (N, W) uint32
    alive: jnp.ndarray       # (N,) bool
    t_ms: jnp.ndarray        # () float32
    key: jnp.ndarray         # PRNG key
    queries_tx: jnp.ndarray  # (N,) int32 FIND_NODE requests sent
    queries_rx: jnp.ndarray  # (N,) int32 FIND_NODE requests served
    rt_fails: jnp.ndarray    # (N, B, K) int32 failed dials per table entry
    rt_retry_ms: jnp.ndarray  # (N, B, K) float32 backoff deadline per entry


def init_kad_state(
    n: int, n_buckets: int = 24, k_bucket: int = 16, seed: int = 0
) -> KadState:
    return KadState(
        rtable=jnp.full((n, n_buckets, k_bucket), -1, dtype=jnp.int32),
        keys=jnp.asarray(make_keys(n, seed)),
        alive=jnp.ones((n,), dtype=bool),
        t_ms=jnp.asarray(0.0, jnp.float32),
        key=jax.random.PRNGKey(seed ^ 0x6AD),
        queries_tx=jnp.zeros((n,), jnp.int32),
        queries_rx=jnp.zeros((n,), jnp.int32),
        rt_fails=jnp.zeros((n, n_buckets, k_bucket), jnp.int32),
        rt_retry_ms=jnp.zeros((n, n_buckets, k_bucket), jnp.float32),
    )


def _segment_rank(sort_key: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """rank[i] = occurrence index of sort_key[i] among equal keys (array
    order); jit-friendly analog of graph._cumcount. Returns (rank, order)."""
    m = sort_key.shape[0]
    order = jnp.argsort(sort_key, stable=True)
    sk = sort_key[order]
    is_start = jnp.concatenate([jnp.ones((1,), bool), sk[1:] != sk[:-1]])
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, jnp.arange(m), 0)
    )
    rank_sorted = jnp.arange(m) - start
    rank = jnp.zeros((m,), jnp.int32).at[order].set(rank_sorted.astype(jnp.int32))
    return rank, order


def _insert_one(table: jnp.ndarray, keys: jnp.ndarray, owner: jnp.ndarray,
                cands: jnp.ndarray) -> jnp.ndarray:
    """Insert candidate peer ids into one owner's (B, K) table.

    Kademlia bucket policy: keep existing entries (the reference's LRU
    preference without the ping-eviction probe), append new distinct entries
    into free slots, drop the rest. Pure fixed-shape: compute each candidate's
    target (bucket, position) and scatter with out-of-bounds drop."""
    b, k = table.shape
    valid = (cands >= 0) & (cands != owner)
    d = _dist(keys, cands, keys[owner])
    slot = bucket_slot(d, b)

    # drop candidates already present in their target bucket
    in_bucket = table[slot]                      # (E, K)
    dup_existing = (in_bucket == cands[:, None]).any(axis=-1)
    # drop repeats within the batch (keep first occurrence)
    eq = cands[:, None] == cands[None, :]
    dup_within = (jnp.tril(eq, k=-1)).any(axis=-1)
    keep = valid & ~dup_existing & ~dup_within

    occupancy = (table >= 0).sum(axis=-1)        # (B,)
    rank, _ = _segment_rank(jnp.where(keep, slot, b).astype(jnp.int32))
    pos = occupancy[slot] + rank
    ok = keep & (pos < k)
    return table.at[
        jnp.where(ok, slot, b), jnp.where(ok, pos, 0)
    ].set(jnp.where(ok, cands, -1).astype(table.dtype), mode="drop")


@jax.jit
def rtable_insert(state: KadState, owners: jnp.ndarray, cands: jnp.ndarray
                  ) -> KadState:
    """Batch insert: owners (M,) each learn cands (M, E). Owner rows must be
    distinct within a batch (callers vmap over distinct lookup origins)."""
    new_rows = jax.vmap(_insert_one, in_axes=(0, None, 0, 0))(
        state.rtable[owners], state.keys, owners, cands
    )
    return state.replace(rtable=state.rtable.at[owners].set(new_rows))


def _closest_from_table(table: jnp.ndarray, keys: jnp.ndarray,
                        target_key: jnp.ndarray, k_out: int) -> jnp.ndarray:
    """The K_RESP closest entries of one (B, K) table to target (-1 padded) —
    a FIND_NODE response (the reference returns the k nearest from the
    routing table)."""
    flat = table.reshape(-1)
    order = lex_argsort(_dist(keys, flat, target_key))
    best = flat[order[:k_out]]
    return best


def _teach_learners(state: KadState, flat_peers: jnp.ndarray,
                    flat_origin: jnp.ndarray, extra_ok=None,
                    e_cap: int = 8) -> KadState:
    """Group flat (learner <- candidate) events by learner with
    capacity-bounded segment ranks and batch-insert into every learner's
    table — the shared scatter behind find_node's query-learning pass and
    connect_found's dial-backs."""
    n = state.rtable.shape[0]
    rank, _ = _segment_rank(jnp.where(flat_peers >= 0, flat_peers, n))
    ok = (flat_peers >= 0) & (rank < e_cap)
    if extra_ok is not None:
        ok = ok & extra_ok
    learn = jnp.full((n, e_cap), -1, jnp.int32).at[
        jnp.where(ok, flat_peers, n), jnp.where(ok, rank, 0)
    ].set(jnp.where(ok, flat_origin, -1), mode="drop")
    return rtable_insert(state, jnp.arange(n, dtype=jnp.int32), learn)


def _pick_alpha(sl: jnp.ndarray, rank: jnp.ndarray, cand: jnp.ndarray,
                s: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Select the ALPHA closest candidate shortlist entries by distance rank
    and gather their ids into a dense (Q, ALPHA) block (-1 padded). Shared
    by find_node and servicedisco.lookup so the two walks cannot diverge."""
    pick_prio = jnp.where(cand, rank, s + 1)
    pick = (jnp.argsort(jnp.argsort(pick_prio, axis=-1), axis=-1)
            < ALPHA) & cand
    p_order = jnp.argsort(~pick, axis=-1, stable=True)[:, :ALPHA]
    p_ids = jnp.take_along_axis(jnp.where(pick, sl, -1), p_order, axis=-1)
    return pick, p_ids


def _merge_shortlist(keys: jnp.ndarray, sl: jnp.ndarray, queried: jnp.ndarray,
                     pick: jnp.ndarray, resp: jnp.ndarray,
                     targets: jnp.ndarray, s: int
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Merge FIND_NODE responses into the shortlist: concat, dedup keeping
    the queried copy of an id (sort key = id*2 + freshness; ids < 2^30 so
    int32 is safe), lex-sort by XOR distance, keep the closest S with their
    queried flags. Shared by find_node and servicedisco.lookup."""
    q = sl.shape[0]
    merged = jnp.concatenate([sl, resp.reshape(q, -1)], axis=-1)
    mq = jnp.concatenate(
        [queried | pick, jnp.zeros((q, merged.shape[1] - s), bool)], axis=-1
    )
    mkey = merged * 2 + jnp.where(mq, 0, 1)
    dorder = jnp.argsort(mkey, axis=-1, stable=True)
    msort = jnp.take_along_axis(merged, dorder, axis=-1)
    qsort = jnp.take_along_axis(mq, dorder, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros((q, 1), bool), msort[:, 1:] == msort[:, :-1]], axis=-1
    )
    msort = jnp.where(dup | (msort < 0), -1, msort)
    md = _dist(keys, msort, targets)
    morder = lex_argsort(md)[:, :s]
    sl_new = jnp.take_along_axis(msort, morder, axis=-1)
    q_new = jnp.take_along_axis(qsort & ~dup, morder, axis=-1)
    return sl_new, q_new


@struct.dataclass
class LookupResult:
    closest: jnp.ndarray     # (Q, K_RESP) int32 final shortlist heads
    hops: jnp.ndarray        # (Q,) int32 rounds until convergence
    latency_ms: jnp.ndarray  # (Q,) float32 wall time of the lookup
    queried: jnp.ndarray     # (Q, rounds*ALPHA) int32 query log (-1 padded)
    n_queries: jnp.ndarray   # (Q,) int32 total FIND_NODE requests


def _find_node_impl(
    state: KadState,
    origins: jnp.ndarray,
    targets: jnp.ndarray,
    stage: jnp.ndarray,
    lat_ms: jnp.ndarray,
    rounds: int,
    shortlist: int,
    attacker: jnp.ndarray | None = None,
    poison0: jnp.ndarray | None = None,
) -> tuple[LookupResult, KadState]:
    """Shared lookup body behind find_node and the DHT adversary's attacked
    lookup (ops/dht_adversary.find_node_attacked). The poison hook is
    python-level: with attacker/poison0 None, the traced program is
    IDENTICAL to the original find_node — the benign path never pays for
    the attack machinery. Armed, every response from an attacker-controlled
    peer is replaced wholesale by `poison0` (the (Q, K_RESP) sybil-directory
    response per target): a lookup eclipse denies honest entries entirely
    instead of merely biasing the merge."""
    n = state.rtable.shape[0]
    q = origins.shape[0]
    s = shortlist

    o_stage = stage[origins]

    def response(peer, target_key):
        """FIND_NODE response of `peer` (masked if dead)."""
        resp = _closest_from_table(state.rtable[peer], state.keys, target_key,
                                   K_RESP)
        return jnp.where(state.alive[peer], resp, -1)

    # seed shortlist from the origin's own table
    sl0 = jax.vmap(
        lambda o, t: _closest_from_table(state.rtable[o], state.keys, t, s)
    )(origins, targets)
    queried0 = jnp.zeros((q, s), bool)

    def round_body(carry, _):
        sl, queried, t_acc, hops, nq = carry
        d = _dist(state.keys, sl, targets)
        order = lex_argsort(d)                            # (Q, S)
        rank = jnp.argsort(order, axis=-1)                # distance rank
        # a node never FIND_NODEs itself over the network, so the origin's
        # own id (distance 0 on self-lookups) is not a query candidate
        cand = ((sl >= 0) & ~queried & state.alive[jnp.clip(sl, 0)]
                & (sl != origins[:, None]))
        # classic termination: the lookup is done once every entry in the
        # top-K_RESP head of the shortlist has been queried
        head_unqueried = (cand & (rank < K_RESP)).any(axis=-1)
        cand = cand & head_unqueried[:, None]
        # pick the ALPHA closest unqueried, by distance rank
        pick, p_ids = _pick_alpha(sl, rank, cand, s)
        any_pick = pick.any(axis=-1)

        resp = jax.vmap(jax.vmap(response, in_axes=(0, None)))(
            jnp.clip(p_ids, 0), targets
        )                                                 # (Q, ALPHA, K_RESP)
        resp = jnp.where((p_ids >= 0)[..., None], resp, -1)
        if attacker is not None:
            # lookup eclipse: a live attacker responder answers with the
            # sybil directory's closest entries instead of its table
            is_att = ((p_ids >= 0) & attacker[jnp.clip(p_ids, 0)]
                      & state.alive[jnp.clip(p_ids, 0)])
            resp = jnp.where(is_att[..., None], poison0[:, None, :], resp)

        # round RTT = max over the parallel queries (iterative lookup waits)
        rtt = 2.0 * lat_ms[o_stage[:, None], stage[jnp.clip(p_ids, 0)]] + PROC_MS
        rtt = jnp.where(p_ids >= 0, rtt, 0.0)
        round_ms = rtt.max(axis=-1)

        sl_new, q_new = _merge_shortlist(
            state.keys, sl, queried, pick, resp, targets, s)

        improved = jnp.any(sl_new != sl, axis=-1) & any_pick
        t_acc = t_acc + jnp.where(any_pick, round_ms, 0.0)
        hops = hops + jnp.where(improved, 1, 0)
        nq = nq + (p_ids >= 0).sum(axis=-1)
        return (sl_new, q_new, t_acc, hops, nq), p_ids

    zeros_q = jnp.zeros((q,), jnp.float32)
    (sl, queried, t_acc, hops, nq), picked_seq = jax.lax.scan(
        round_body,
        (sl0, queried0, zeros_q, jnp.zeros((q,), jnp.int32),
         jnp.zeros((q,), jnp.int32)),
        None,
        length=rounds,
    )
    picked_seq = jnp.moveaxis(picked_seq, 0, 1).reshape(q, -1)  # (Q, R*ALPHA)

    # ---- learning + accounting -------------------------------------------
    # origin learns its final shortlist (every response it accepted)
    state = rtable_insert(state, origins, sl)
    # each queried peer learns the origins that queried it: group the
    # (learner, origin) events by learner (segment ranks, capacity-bounded)
    # so parallel lookups hitting the same responder all land
    flat_peers = picked_seq.reshape(-1)
    flat_origin = jnp.broadcast_to(origins[:, None], picked_seq.shape).reshape(-1)
    state = _teach_learners(state, flat_peers, flat_origin)

    served = jnp.zeros((n,), jnp.int32).at[
        jnp.where(flat_peers >= 0, flat_peers, n)
    ].add(1, mode="drop")
    state = state.replace(
        queries_tx=state.queries_tx.at[origins].add(nq),
        queries_rx=state.queries_rx + served,
    )

    result = LookupResult(
        closest=sl[:, :K_RESP], hops=hops, latency_ms=t_acc,
        queried=picked_seq, n_queries=nq,
    )
    return result, state


@partial(jax.jit, static_argnames=("rounds", "shortlist"))
def find_node(
    state: KadState,
    origins: jnp.ndarray,     # (Q,) int32 distinct querying peers
    targets: jnp.ndarray,     # (Q, W) uint32 target keys
    stage: jnp.ndarray,       # (N,) int32 topology stage per peer
    lat_ms: jnp.ndarray,      # (S+1, S+1) float32 stage-pair latency
    rounds: int = 6,
    shortlist: int = 32,
) -> tuple[LookupResult, KadState]:
    """Batched iterative FIND_NODE (kad-dht/core.nim warmup/probe primitive).

    Each origin walks the XOR metric toward its target: query the ALPHA
    closest unqueried shortlist peers, merge their K_RESP closest entries,
    repeat `rounds` times (enough for uniform keys at any simulated N: each
    round roughly halves the remaining distance). Per-round wall time is the
    max RTT of the parallel queries, accumulated only while the shortlist
    still improves — matching the iterative lookup's termination ("no peer
    closer than the best seen" => stop counting).

    Returns per-origin results plus state with updated tables (origin learns
    every response entry; queried peers learn the origin) and counters.
    """
    return _find_node_impl(state, origins, targets, stage, lat_ms,
                           rounds, shortlist)


@partial(jax.jit, static_argnames=("max_fails", "backoff_base_ms"))
def evict_failed(state: KadState, origins: jnp.ndarray,
                 found: jnp.ndarray, max_fails: int = 1,
                 backoff_base_ms: float = 0.0) -> KadState:
    """DISCOVERY=extended (KademliaDiscovery) eviction: the discovery layer
    exists to hand the application CONNECTABLE peers, so after the
    end-of-lookup dial-out to the FOUND peers, every dial that fails (a
    dead shortlist entry — queried peers are alive by construction, the
    lookup's candidate filter sees to that) drops the entry from the
    dialer's routing table. Plain KadDHT mode keeps the stale entry (the
    LRU-keep-without-ping-eviction policy of rtable_insert). Buckets are
    re-packed left so the append-position arithmetic of _insert_one stays
    valid.

    Retry budget (the supervisor's backoff idiom, runtime/campaign.py):
    with `max_fails` > 1 a failed dial does not evict immediately — the
    entry's per-slot failure counter increments and the entry goes under
    exponential backoff (`backoff_base_ms * 2**(fails-1)` past state.t_ms);
    while under backoff a repeated failure is NOT re-counted (the dial was
    never retried). Eviction fires only once the counter reaches
    `max_fails`. A successful dial resets the counter and the deadline.
    The default (max_fails=1) reproduces the original immediate-eviction
    tables exactly — an attack cannot get free evictions from one lossy
    round unless the operator opted out of retries.

    `found`: (Q, K) shortlist heads each origin dials
    (LookupResult.closest)."""
    dead = ~state.alive
    t = state.t_ms

    def evict_one(table, fails, retry, f_ids):
        bad_ids = jnp.where((f_ids >= 0) & dead[jnp.clip(f_ids, 0)],
                            f_ids, -2)
        is_bad = (table[..., None] == bad_ids).any(axis=-1)
        good_ids = jnp.where((f_ids >= 0) & ~dead[jnp.clip(f_ids, 0)],
                             f_ids, -2)
        is_good = (table[..., None] == good_ids).any(axis=-1)
        # entries under backoff were not re-dialed this wave: no new count
        fail_event = is_bad & ~(retry > t)
        fails = jnp.where(fail_event, fails + 1, fails)
        fails = jnp.where(is_good, 0, fails)
        evict = fail_event & (fails >= max_fails)
        retry = jnp.where(
            fail_event & ~evict,
            t + backoff_base_ms * jnp.exp2((fails - 1).astype(jnp.float32)),
            retry)
        retry = jnp.where(is_good, 0.0, retry)
        marked = jnp.where(evict, -1, table)
        fails = jnp.where(evict, 0, fails)
        retry = jnp.where(evict, 0.0, retry)
        # compact each bucket: keep entries left-packed, holes to the right
        # (the shadow arrays repack with the table so slots stay aligned)
        order = jnp.argsort(marked < 0, axis=-1, stable=True)
        return (jnp.take_along_axis(marked, order, axis=-1),
                jnp.take_along_axis(fails, order, axis=-1),
                jnp.take_along_axis(retry, order, axis=-1))

    new_rows, new_fails, new_retry = jax.vmap(evict_one)(
        state.rtable[origins], state.rt_fails[origins],
        state.rt_retry_ms[origins], found)
    return state.replace(
        rtable=state.rtable.at[origins].set(new_rows),
        rt_fails=state.rt_fails.at[origins].set(new_fails),
        rt_retry_ms=state.rt_retry_ms.at[origins].set(new_retry),
    )


@jax.jit
def connect_found(state: KadState, origins: jnp.ndarray,
                  found: jnp.ndarray) -> KadState:
    """DISCOVERY=extended (KademliaDiscovery, kad-dht/helpers.nim:48-57)
    dial-backs: after a lookup the origin connects to the peers it found,
    so every live entry of the final shortlist learns the origin. Plain
    KadDHT mode only teaches the origin to the peers it QUERIED
    (find_node's learning pass).

    `found`: (Q, K) shortlist heads per origin (LookupResult.closest)."""
    flat_peers = found.reshape(-1)
    flat_origin = jnp.broadcast_to(
        origins[:, None], found.shape).reshape(-1)
    # dead peers answer no dial; self-dials don't happen
    extra_ok = ((flat_peers != flat_origin)
                & state.alive[jnp.clip(flat_peers, 0)])
    return _teach_learners(state, flat_peers, flat_origin, extra_ok)


@jax.jit
def seed_bootstraps(state: KadState, bootstraps: jnp.ndarray) -> KadState:
    """Every peer seeds its table with the bootstrap anchors and every
    bootstrap learns every peer — the array form of connectToBootstraps +
    the bootstrap's passive accumulation (kad-dht/helpers.nim:62-91,
    regression/kad_utils.nim:88-94)."""
    n = state.rtable.shape[0]
    all_peers = jnp.arange(n, dtype=jnp.int32)
    cands = jnp.broadcast_to(bootstraps[None, :], (n, bootstraps.shape[0]))
    state = rtable_insert(state, all_peers, cands)
    # bootstraps learn everyone (batched over bootstraps; N candidates each)
    nb = bootstraps.shape[0]
    state = rtable_insert(
        state, bootstraps, jnp.broadcast_to(all_peers[None, :], (nb, n))
    )
    return state


def rtable_census(state: KadState) -> jnp.ndarray:
    """Per-peer routing-table population — the reference's warmup census
    (kad-dht/core.nim:17-22 'Kad routing table, peers = rtPeers')."""
    return (state.rtable >= 0).sum(axis=(-1, -2)).astype(jnp.int32)


def random_targets(key: jnp.ndarray, q: int) -> jnp.ndarray:
    """Random lookup targets — getRandomPeerId (kad-dht/helpers.nim:10-12):
    uniform keys that (almost surely) match no live node."""
    return jax.random.bits(key, (q, KEY_WORDS), dtype=jnp.uint32)


def true_closest(keys: np.ndarray, target: np.ndarray, k: int = 1) -> np.ndarray:
    """Host-side brute-force ground truth for tests: the k globally closest
    node indices to target under the XOR metric."""
    ints = np.zeros(keys.shape[0], dtype=object)
    t_int = 0
    for w in range(KEY_WORDS):
        ints = ints * (1 << 32) + keys[:, w].astype(object)
        t_int = t_int * (1 << 32) + int(target[w])
    d = np.array([x ^ t_int for x in ints], dtype=object)
    return np.argsort(d, kind="stable")[:k]
