"""Executable GossipSub v1.1 reference model — the conformance oracle's spec side.

A pure-host (numpy) transcription of the per-heartbeat transition relation
the compiled engine implements: mesh GRAFT/PRUNE with backoff, score-floor
eviction, PX capture on PRUNE, opportunistic grafting, score decay with the
zero-cutoff, fanout TTL expiry, the eight attack-round behaviors of
ops/adversary.py, the adaptive controller state machine, and the fault
transforms of ops/faults.py. The transition functions follow the ACL2s
formalization of GossipSub (arXiv:2311.08859): state is explicit, every
transition is a total function of (state, topology, params), and the honest
defense rules (backoff violation, graylist refusal, score-gated graft
acceptance) are written as guards, not side effects.

The one deliberate deviation from the ACL2s spec: where the formal model
leaves peer SELECTION nondeterministic (graft targets, prune survivors), this
model fixes the selection oracle to the engine's PRNG stream — it performs
the same `jax.random.split`/`uniform` calls host-side on the carried key
(threefry is bit-deterministic, in or out of jit) and resolves ties with the
same stable-sort ranks. That turns the spec's transition RELATION into a
transition FUNCTION pointwise-comparable with the compiled step, so the
differential harness (analysis/conformance.py) can diff full state
trajectories field-by-field instead of checking membership in a set of
allowed successors.

Nothing here is jitted and nothing runs on a device; `jax.random` is used
only as the selection oracle. Numerics discipline: every float array stays
float32 and every scalar constant is wrapped in np.float32 so host arithmetic
performs the same IEEE-754 single ops, in the same order, as the XLA:CPU
program — on matching op order the two sides agree bitwise, which is what
lets the harness demand exact equality on bool/int fields and ulp-tight
tolerance on floats.
"""

from __future__ import annotations

import numpy as np

from .state import PX_POOL_WIDTH, SimParams, SimState, repair_inert

BIG = np.float32(1e30)
INF = np.float32(3.4e38)

# every SimState leaf the oracle tracks and the differential compares;
# `key` rides alongside (as the jax key array) but is compared via the
# trajectory it induces, not bit-by-bit
SPEC_FIELDS = (
    "mesh_mask", "fanout_mask", "fanout_expire", "backoff_until", "fmd",
    "slow_penalty", "alive", "subscribed", "hb_phase", "uplink_free_ms",
    "rx_free_ms", "warm_offset_ms", "t_ms", "grafts", "grafts_rx", "prunes",
    "prunes_rx", "bytes_tx", "bytes_rx", "dup_rx", "ihave_tx", "iwant_tx",
    "ihave_rx", "iwant_rx", "idontwant_tx", "idontwant_rx", "px_pool",
    "starve_hb", "evictions", "px_grafts", "redials",
)


def host_state(state: SimState) -> dict:
    """SimState -> the oracle's state dict: one numpy array per leaf, plus
    the carried jax PRNG key (left as a jax array for splitting)."""
    st = {f: np.asarray(getattr(state, f)) for f in SPEC_FIELDS}
    st["key"] = state.key
    return st


def _ranks(priority: np.ndarray) -> np.ndarray:
    """Per-row rank under ascending priority — the double argsort of
    ops/heartbeat._ranks. kind="stable" matches XLA's stable sort, so equal
    keys rank in slot order on both sides."""
    return np.argsort(np.argsort(priority, axis=-1, kind="stable"),
                      axis=-1, kind="stable")


def _apply_decay(arr: np.ndarray, scale: float, params: SimParams):
    eff = (arr * np.float32(scale)).astype(np.float32)
    return np.where(eff < np.float32(params.decay_to_zero),
                    np.float32(0.0), eff)


def _pull(edge_mask: np.ndarray, conns: np.ndarray, rev: np.ndarray):
    """out[q, j] = edge_mask[conns[q,j], rev[q,j]] — the reciprocal-view
    gather through the edge involution (ops/pull.reciprocal_pull_bool)."""
    out = edge_mask[np.clip(conns, 0, None), np.clip(rev, 0, None)]
    return out & (conns >= 0) & (rev >= 0)


def _nbr_pull(per_peer: np.ndarray, conns: np.ndarray, rev: np.ndarray):
    """out[q, j] = per_peer[conns[q,j]] (ops/pull.neighbor_pull_bool)."""
    return per_peer[np.clip(conns, 0, None)] & (conns >= 0) & (rev >= 0)


def spec_score(st: dict, params: SimParams) -> np.ndarray:
    """v1.1 score subset (ops/state.SimState.score): P2 firstMessageDeliveries
    capped, plus the negative-weighted slow-peer penalty counter."""
    fmd = np.minimum(st["fmd"], np.float32(params.fmd_cap))
    return (np.float32(params.fmd_weight) * fmd
            + np.float32(params.slow_weight) * st["slow_penalty"])


def _score_of(fmd, slow_penalty, params: SimParams) -> np.ndarray:
    fmd = np.minimum(fmd, np.float32(params.fmd_cap))
    return (np.float32(params.fmd_weight) * fmd
            + np.float32(params.slow_weight) * slow_penalty)


def opportunistic_graft_candidates(mesh, valid, backoff, t, scores,
                                   params: SimParams,
                                   highest_slot_ties: bool = False):
    """v1.1 opportunistic-grafting selection with the tie policy made
    explicit — the spec-side transcription of the engine's og block
    (ops/heartbeat.py) and of the ACL2s formalization's opportunistic-
    grafting rule (arXiv:2311.08859).

    Rule: when a row's UPPER-MEDIAN mesh score (sorted[deg // 2], the
    libp2p implementations' median) sinks below
    params.opportunistic_graft_threshold and the mesh is non-empty, graft
    up to 2 eligible peers (valid, non-mesh, backoff expired) scoring
    STRICTLY above that median, preferring the highest-scored.

    Tie policy: the ACL2s model leaves the choice among equally-scored
    candidates NONDETERMINISTIC (any maximal subset of size <= 2 is an
    allowed successor). This executable spec — per the module-wide
    selection-oracle convention — resolves it deterministically to the
    LOWEST NEIGHBOR SLOT: ranks come from a stable double argsort, so
    among equal -score keys the earlier slot wins, exactly matching the
    engine's jnp.argsort (stable by default in JAX). Two further
    median-rule consequences the differential pins: candidates scoring
    EXACTLY the median are excluded (strict >), and the median index for
    even degrees is the upper middle, not the average.

    Returns (og, median, low): the (N, C) selected graft edge set and the
    per-row median/low-quality diagnostics the caller's guards reuse."""
    n, c = mesh.shape
    deg = mesh.sum(axis=-1)
    msort = np.sort(np.where(mesh, scores, BIG), axis=-1, kind="stable")
    k_med = np.clip(deg // 2, 0, c - 1)
    median = np.take_along_axis(msort, k_med[:, None], axis=-1)[:, 0]
    low = ((median < np.float32(params.opportunistic_graft_threshold))
           & (deg > 0))
    og_elig = (valid & ~mesh & (backoff <= t)
               & (scores > median[:, None]) & low[:, None])
    og_prio = np.where(og_elig, -scores, BIG)
    if highest_slot_ties:
        # the OTHER admissible resolution of the ACL2s nondeterminism
        # (highest slot first among equal scores) — the differential's
        # tie-policy witness: flipping this knob must produce divergence
        # whenever a tie was decisive, proving the walk pins the policy
        og = (_ranks(og_prio[:, ::-1])[:, ::-1] < 2) & og_elig
    else:
        og = (_ranks(og_prio) < 2) & og_elig
    return og, median, low


def _validity(st, conns, rev, alive, edge_ok):
    nbr_ok = _nbr_pull(alive & st["subscribed"], conns, rev)
    valid = ((conns >= 0) & alive[:, None] & nbr_ok
             & st["subscribed"][:, None])
    if edge_ok is not None:
        valid = valid & edge_ok
    return valid


def spec_heartbeat(st: dict, conns, rev, out_mask, params: SimParams,
                   edge_ok=None, og_tie_highest: bool = False) -> dict:
    """One heartbeat of the reference transition relation — the spec twin of
    ops/heartbeat.heartbeat_step on its per-step (non-deferred-decay) path.
    Branch guards mirror the engine's lax.cond predicates exactly: a guard
    that does not fire leaves its fields untouched AND consumes no extra
    randomness (both k_graft and k_keep are split unconditionally)."""
    import jax

    st = dict(st)
    n, c = conns.shape
    key, k_graft, k_keep, k_churn_d, k_churn_u = jax.random.split(st["key"], 5)
    t = np.float32(st["t_ms"])

    # -- churn --------------------------------------------------------------
    alive = st["alive"]
    if params.churn_down_per_hb > 0.0 or params.churn_up_per_hb > 0.0:
        dies = (np.asarray(jax.random.uniform(k_churn_d, (n,)))
                < np.float32(params.churn_down_per_hb))
        revives = (np.asarray(jax.random.uniform(k_churn_u, (n,)))
                   < np.float32(params.churn_up_per_hb))
        alive = np.where(alive, ~dies, revives)
        warm = np.full_like(st["warm_offset_ms"], INF)
    else:
        warm = st["warm_offset_ms"]

    valid = _validity(st, conns, rev, alive, edge_ok)
    mesh = st["mesh_mask"] & valid
    deg = mesh.sum(axis=-1)

    # score() is read at several guard points within one step; none of the
    # in-step writes (mesh, backoff) feed it, so one evaluation serves all
    scores = spec_score(st, params)
    zeros_n = np.zeros((n,), np.int32)

    # -- GRAFT --------------------------------------------------------------
    need = np.where(deg < params.d_low, params.d - deg, 0)
    graft_tx_inc = graft_rx_inc = zeros_n
    if (need > 0).any():
        eligible = (valid & ~mesh & (st["backoff_until"] <= t)
                    & (scores >= np.float32(0.0)))
        u = np.asarray(jax.random.uniform(k_graft, (n, c)))
        g_prio = np.where(eligible, u, BIG)
        grafted = (_ranks(g_prio) < need[:, None]) & eligible
        graft_rx = _pull(grafted, conns, rev)
        mesh = (mesh | grafted | graft_rx) & valid
        deg2 = mesh.sum(axis=-1)
        graft_tx_inc = grafted.sum(axis=-1, dtype=np.int32)
        graft_rx_inc = graft_rx.sum(axis=-1, dtype=np.int32)
    else:
        deg2 = deg

    # -- PRUNE --------------------------------------------------------------
    over = deg2 > params.d_high
    backoff = st["backoff_until"]
    prune_tx_inc = prune_rx_inc = zeros_n
    pruned_rx = np.zeros((n, c), dtype=bool)
    if over.any():
        rand_keep = np.asarray(jax.random.uniform(k_keep, (n, c)))
        s_prio = np.where(mesh, -scores + np.float32(1e-3) * rand_keep, BIG)
        top_score = (_ranks(s_prio) < params.d_score) & mesh
        out_in_top = (top_score & out_mask).sum(axis=-1)
        need_out = np.clip(params.d_out - out_in_top, 0, params.d)
        o_prio = np.where(mesh & out_mask & ~top_score, rand_keep, BIG)
        keep_out = ((_ranks(o_prio) < need_out[:, None])
                    & mesh & out_mask & ~top_score)
        base = top_score | keep_out
        need_fill = np.clip(params.d - base.sum(axis=-1), 0, params.d)
        f_prio = np.where(mesh & ~base, rand_keep, BIG)
        keep = base | ((_ranks(f_prio) < need_fill[:, None]) & mesh & ~base)
        pruned = mesh & ~keep & over[:, None]
        mesh = mesh & ~pruned
        pruned_by_peer = _pull(pruned, conns, rev)
        backoff = np.where(pruned | pruned_by_peer,
                           t + np.float32(params.prune_backoff_ms), backoff)
        mesh = mesh & ~pruned_by_peer
        prune_tx_inc = pruned.sum(axis=-1, dtype=np.int32)
        prune_rx_inc = pruned_by_peer.sum(axis=-1, dtype=np.int32)
        pruned_rx = pruned_by_peer

    # -- score eviction (opt-in) --------------------------------------------
    ev_tx_inc = ev_rx_inc = zeros_n
    ev_rx_edges = np.zeros((n, c), dtype=bool)
    if params.evict:
        ev_cand = mesh & (scores < np.float32(params.eviction_threshold))
        if ev_cand.any():
            ev_rx = _pull(ev_cand, conns, rev)
            backoff = np.where(ev_cand | ev_rx,
                               t + np.float32(params.prune_backoff_ms),
                               backoff)
            mesh = mesh & ~ev_cand & ~ev_rx
            ev_tx_inc = ev_cand.sum(axis=-1, dtype=np.int32)
            ev_rx_inc = ev_rx.sum(axis=-1, dtype=np.int32)
            ev_rx_edges = ev_rx

    # -- PX on PRUNE (opt-in) -----------------------------------------------
    px_pool = st["px_pool"]
    if params.px:
        got_pruned = pruned_rx | ev_rx_edges
        if got_pruned.any():
            elig = valid & (scores >= np.float32(0.0))
            prio = (np.where(elig, -scores, BIG)
                    + np.float32(1e-4) * np.arange(c, dtype=np.float32))
            w = min(PX_POOL_WIDTH, c)
            order = np.argsort(prio, axis=-1, kind="stable")[:, :w]
            take_ok = (np.take_along_axis(elig, order, axis=-1)
                       & (np.arange(w) < params.px_count))
            cand = np.where(take_ok,
                            np.take_along_axis(conns, order, axis=-1),
                            np.int32(-1)).astype(np.int32)
            if w < PX_POOL_WIDTH:
                cand = np.pad(cand, ((0, 0), (0, PX_POOL_WIDTH - w)),
                              constant_values=-1)
            got = got_pruned.any(axis=-1)
            i0 = got_pruned.argmax(axis=-1)
            pruner = np.take_along_axis(conns, i0[:, None], axis=1)[:, 0]
            advert = cand[np.clip(pruner, 0, None)]
            advert = np.where(
                advert == np.arange(n, dtype=np.int32)[:, None],
                np.int32(-1), advert)
            px_pool = np.where(got[:, None], advert, px_pool)

    # -- opportunistic grafting (opt-in) ------------------------------------
    og_tx_inc = og_rx_inc = zeros_n
    if params.opportunistic_graft_threshold > -9999.0:
        og, _, _ = opportunistic_graft_candidates(
            mesh, valid, backoff, t, scores, params,
            highest_slot_ties=og_tie_highest)
        if og.any():
            rx = _pull(og, conns, rev)
            mesh = (mesh | og | rx) & valid
            og_tx_inc = og.sum(axis=-1, dtype=np.int32)
            og_rx_inc = rx.sum(axis=-1, dtype=np.int32)

    # -- score decay --------------------------------------------------------
    fmd, slow = st["fmd"], st["slow_penalty"]
    if ((fmd > 0) | (slow > 0)).any():
        fmd = _apply_decay(fmd, params.fmd_decay, params)
        slow = _apply_decay(slow, params.slow_decay, params)

    # -- fanout TTL expiry --------------------------------------------------
    fanout = st["fanout_mask"]
    if (st["fanout_expire"] > 0.0).any():
        fanout = fanout & (t < st["fanout_expire"])[:, None]

    prunes_new = st["prunes"] + prune_tx_inc
    prunes_rx_new = st["prunes_rx"] + prune_rx_inc
    if params.evict:
        prunes_new = prunes_new + ev_tx_inc
        prunes_rx_new = prunes_rx_new + ev_rx_inc
        st["evictions"] = st["evictions"] + ev_tx_inc
    if params.px:
        st["px_pool"] = px_pool
    st.update(
        mesh_mask=mesh, fanout_mask=fanout, backoff_until=backoff,
        fmd=fmd, slow_penalty=slow, alive=alive, warm_offset_ms=warm,
        t_ms=np.float32(t + np.float32(params.heartbeat_ms)), key=key,
        grafts=st["grafts"] + graft_tx_inc + og_tx_inc,
        grafts_rx=st["grafts_rx"] + graft_rx_inc + og_rx_inc,
        prunes=prunes_new, prunes_rx=prunes_rx_new,
    )
    return st


def spec_adversary_round(st: dict, conns, rev, attacker, params: SimParams,
                         adv, hb_idx: int, edge_ok=None) -> dict:
    """One attacker round + honest defense accounting, applied after
    spec_heartbeat — the spec twin of ops/adversary.adversary_round. The
    scenario dispatch mirrors the engine's derived-behavior properties
    (graft_flood covers the sybil/eclipse/cold-boot/rotation family)."""
    st = dict(st)
    n, c = conns.shape
    t = np.float32(st["t_ms"])
    valid = _validity(st, conns, rev, st["alive"], edge_ok)
    att_row = attacker[:, None] & valid

    mesh = st["mesh_mask"]
    slow_penalty = st["slow_penalty"]
    uplink_free_ms = st["uplink_free_ms"]
    backoff_until = st["backoff_until"]
    fmd = st["fmd"]

    if adv.identity_rotation:
        if (hb_idx % adv.rotation_period_hb) == adv.rotation_period_hb - 1:
            inc = ((attacker[:, None] | _nbr_pull(attacker, conns, rev))
                   & (conns >= 0))
            mesh = mesh & ~inc
            slow_penalty = np.where(inc, np.float32(0.0), slow_penalty)
            fmd = np.where(inc, np.float32(0.0), fmd)
            backoff_until = np.where(inc, np.float32(0.0), backoff_until)

    if adv.graft_flood:
        flood = att_row
        rx = _pull(flood, conns, rev)
        violation = rx & ((backoff_until > t) | mesh)
        # rotation reads the post-scrub counters; everything else the
        # pre-round ones — for non-rotation scenarios the locals ARE the
        # pre-round arrays, so one formula serves both branches
        sc = _score_of(fmd, slow_penalty, params)
        accept = rx & ~violation & (sc >= np.float32(0.0))
        mesh = (mesh | flood | accept) & valid
        slow_penalty = slow_penalty + np.where(
            violation, np.float32(adv.violation_penalty), np.float32(0.0))
        st["grafts"] = st["grafts"] + flood.sum(axis=-1, dtype=np.int32)
        st["grafts_rx"] = st["grafts_rx"] + rx.sum(axis=-1, dtype=np.int32)

    if adv.ihave_spam:
        ann = att_row
        rx_ann = _pull(ann, conns, rev)
        k = np.int32(adv.spam_ihaves_per_hb)
        st["ihave_tx"] = st["ihave_tx"] + ann.sum(axis=-1, dtype=np.int32) * k
        st["ihave_rx"] = (st["ihave_rx"]
                          + rx_ann.sum(axis=-1, dtype=np.int32) * k)
        st["iwant_tx"] = (st["iwant_tx"]
                          + rx_ann.sum(axis=-1, dtype=np.int32) * k)
        st["iwant_rx"] = st["iwant_rx"] + ann.sum(axis=-1, dtype=np.int32) * k
        slow_penalty = slow_penalty + np.where(
            rx_ann, np.float32(adv.violation_penalty), np.float32(0.0))

    if adv.iwant_spam:
        req = att_row
        rx_req = _pull(req, conns, rev)
        k = np.int32(adv.spam_iwants_per_hb)
        sc0 = spec_score(st, params)
        serve = rx_req & (sc0 >= np.float32(params.graylist_threshold))
        served = serve.sum(axis=-1, dtype=np.int32) * k
        st["iwant_tx"] = st["iwant_tx"] + req.sum(axis=-1, dtype=np.int32) * k
        st["iwant_rx"] = (st["iwant_rx"]
                          + rx_req.sum(axis=-1, dtype=np.int32) * k)
        uplink_free_ms = np.where(
            served > 0,
            np.maximum(uplink_free_ms, t)
            + served.astype(np.float32) * np.float32(adv.iwant_answer_ms),
            uplink_free_ms)
        slow_penalty = slow_penalty + np.where(
            rx_req, np.float32(adv.violation_penalty), np.float32(0.0))

    if adv.slow_mimicry and params.slow_weight < 0.0:
        c_req = params.graylist_threshold / params.slow_weight
        att_view = _nbr_pull(attacker, conns, rev)
        slow_penalty = np.where(
            valid & att_view,
            np.float32(adv.mimic_margin * c_req), slow_penalty)

    st.update(mesh_mask=mesh, slow_penalty=slow_penalty,
              uplink_free_ms=uplink_free_ms)
    if adv.identity_rotation:
        st.update(fmd=fmd, backoff_until=backoff_until)
    return st


def spec_adaptive_round(st: dict, ctrl: dict, conns, rev, attacker,
                        params: SimParams, adv, hb_idx: int,
                        edge_ok=None) -> tuple[dict, dict]:
    """The adaptive controller round (ops/adversary.adaptive_round):
    PREDICT -> ACT/THROTTLE -> OBSERVE -> POISON over the ctrl dict
    {viol_est, regrafts, px_injected, throttled_hb}."""
    pol = adv.adaptive
    st, ctrl = dict(st), dict(ctrl)
    n, c = conns.shape
    t = np.float32(st["t_ms"])
    valid = _validity(st, conns, rev, st["alive"], edge_ok)
    att_row = attacker[:, None] & valid
    me = np.arange(n, dtype=np.int32)

    if pol.duty_cycle and params.slow_weight < 0.0:
        c_req = np.float32(params.graylist_threshold / params.slow_weight)
        predicted = (ctrl["viol_est"] * np.float32(params.slow_decay)
                     + np.float32(adv.violation_penalty))
        act = attacker & (predicted < np.float32(pol.throttle_margin) * c_req)
    else:
        act = attacker

    legal = att_row & (st["backoff_until"] <= t) & ~st["mesh_mask"]
    graft = att_row & act[:, None]
    if pol.regraft:
        graft = graft | legal
    rx = _pull(graft, conns, rev)
    violation = rx & ((st["backoff_until"] > t) | st["mesh_mask"])
    sc = spec_score(st, params)
    accept = rx & ~violation & (sc >= np.float32(0.0))
    mesh = (st["mesh_mask"] | graft | accept) & valid
    slow_penalty = st["slow_penalty"] + np.where(
        violation, np.float32(adv.violation_penalty), np.float32(0.0))
    st["grafts"] = st["grafts"] + graft.sum(axis=-1, dtype=np.int32)
    st["grafts_rx"] = st["grafts_rx"] + rx.sum(axis=-1, dtype=np.int32)

    self_viol = (graft & ((st["backoff_until"] > t)
                          | st["mesh_mask"])).any(axis=-1)
    ctrl["viol_est"] = (ctrl["viol_est"] * np.float32(params.slow_decay)
                        + np.where(attacker & self_viol,
                                   np.float32(adv.violation_penalty),
                                   np.float32(0.0)))
    if pol.regraft:
        ctrl["regrafts"] = ctrl["regrafts"] + np.where(
            attacker, legal.sum(axis=-1, dtype=np.int32), np.int32(0))
    ctrl["throttled_hb"] = (ctrl["throttled_hb"]
                            + (attacker & ~act).astype(np.int32))

    if pol.px_poison and not repair_inert(params):
        att_sorted = np.sort(np.where(attacker, me, np.int32(n)))
        n_att = np.int32(attacker.sum())
        att_nbr = _nbr_pull(attacker, conns, rev)
        victim = (~attacker & st["alive"] & st["subscribed"]
                  & (att_nbr & valid).any(axis=-1))
        pool = st["px_pool"].copy()
        base = me + np.int32(hb_idx) * np.int32(pol.px_poison_per_hb)
        denom = max(int(n_att), 1)
        for k in range(pol.px_poison_per_hb):
            cand = att_sorted[(base + np.int32(k)) % denom]
            empty = pool < 0
            slot = empty.argmax(axis=-1)
            do = victim & (n_att > 0) & (cand < n) & empty.any(axis=-1)
            pool[me, slot] = np.where(do, cand, pool[me, slot])
            ctrl["px_injected"] = ctrl["px_injected"] + do.astype(np.int32)
        st["px_pool"] = pool

    st.update(mesh_mask=mesh, slow_penalty=slow_penalty)
    return st, ctrl


def spec_censorship_penalty(st: dict, conns, rev, attacker, received,
                            params: SimParams, adv) -> dict:
    """Post-publish P3 analog (ops/adversary.censorship_penalty_update)."""
    if float(adv.censor_penalty) == 0.0:
        return st
    st = dict(st)
    att_nbr = _nbr_pull(attacker, conns, rev)
    deficit = (st["mesh_mask"] & att_nbr
               & (received & ~attacker)[:, None])
    st["slow_penalty"] = st["slow_penalty"] + np.where(
        deficit, np.float32(adv.censor_penalty), np.float32(0.0))
    return st


def spec_eclipse_setup(st: dict, conns, attacker, publisher: int) -> dict:
    """ops/adversary.eclipse_setup: the publisher's mesh row collapses onto
    its attacker edges the moment the eclipse closes."""
    st = dict(st)
    row = np.where(conns[publisher] >= 0,
                   attacker[np.clip(conns[publisher], 0, None)], False)
    mesh = st["mesh_mask"].copy()
    mesh[publisher] = row
    st["mesh_mask"] = mesh
    return st


# -- fault transforms (ops/faults.py scan-body conds, as host functions) ----

def spec_go_dark(st: dict, crash) -> dict:
    st = dict(st)
    st["alive"] = st["alive"] & ~crash
    st["warm_offset_ms"] = np.full_like(st["warm_offset_ms"], INF)
    return st


def spec_restart(st: dict, crash, conns, rev, params: SimParams) -> dict:
    st = dict(st)
    crash_nbr = _nbr_pull(crash, conns, rev)
    inc = (crash[:, None] | crash_nbr) & (conns >= 0)
    st["alive"] = st["alive"] | crash
    st["mesh_mask"] = st["mesh_mask"] & ~inc
    st["fmd"] = np.where(inc, np.float32(0.0), st["fmd"])
    st["slow_penalty"] = np.where(inc, np.float32(0.0), st["slow_penalty"])
    st["backoff_until"] = np.where(inc, np.float32(0.0), st["backoff_until"])
    st["warm_offset_ms"] = np.full_like(st["warm_offset_ms"], INF)
    if not repair_inert(params):
        st["px_pool"] = np.where(crash[:, None], np.int32(-1), st["px_pool"])
        st["starve_hb"] = np.where(crash, np.int32(0), st["starve_hb"])
    return st


def spec_partition_edge_mask(side, conns) -> np.ndarray:
    return (conns >= 0) & (side[:, None] ^ side[np.clip(conns, 0, None)])


def spec_freeze(st: dict, cross) -> tuple[dict, np.ndarray]:
    st = dict(st)
    frozen = st["mesh_mask"] & cross
    st["mesh_mask"] = st["mesh_mask"] & ~cross
    return st, frozen


def spec_thaw(st: dict, frozen, conns) -> tuple[dict, np.ndarray]:
    st = dict(st)
    ok = st["alive"] & st["subscribed"]
    keep = frozen & ok[:, None] & ok[np.clip(conns, 0, None)]
    st["mesh_mask"] = st["mesh_mask"] | keep
    return st, np.zeros_like(frozen)


def spec_spike(st: dict, spike, spike_ms: float) -> dict:
    st = dict(st)
    t = np.float32(st["t_ms"])
    st["uplink_free_ms"] = np.where(
        spike,
        np.maximum(st["uplink_free_ms"], t) + np.float32(spike_ms),
        st["uplink_free_ms"])
    return st
