"""GossipSub heartbeat as a jit-compiled array step (reference L0 behavior).

One call = one heartbeat of the protocol the reference delegates to
nim-libp2p/go-libp2p-pubsub/rust-libp2p (configured in
gossipsub-queues/main.nim:252-332): mesh rebalance (graft when |mesh| < D_low
up to D, prune when |mesh| > D_high down to D keeping the D_score
highest-scored members and at least D_out outbound members), PRUNE backoff
bookkeeping, and peer-score decay.

Everything is a masked fixed-shape op over the (N, C) neighbor-slot arrays;
reciprocity (GRAFT/PRUNE control messages) is a single row-gather pull
through the precomputed reverse-slot involution (ops/graph.py, ops/pull.py),
and the rebalance work runs under lax.cond so a stable mesh skips it
entirely. Dead neighbors (churn) simply fall out of the validity mask and
are replaced on the next rebalance — the elastic-recovery analog of the
reference's dial-retry loops (SURVEY.md §5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .pull import neighbor_pull_bool, reciprocal_pull_bool
from .state import (PX_POOL_WIDTH, SimParams, SimState, repair_inert,
                    restore_repair, strip_repair)

BIG = jnp.float32(1e30)


def _ranks(priority: jnp.ndarray) -> jnp.ndarray:
    """Per-row rank of each slot under ascending priority (double argsort)."""
    return jnp.argsort(jnp.argsort(priority, axis=-1), axis=-1)


def _apply_decay(arr: jnp.ndarray, scale, params: SimParams) -> jnp.ndarray:
    """Geometric decay with the zero-cutoff: where(arr*scale < z, 0, ...).
    The one formula behind per-step decay, deferred-scale score reads, and
    the end-of-scan materialization — keep them identical."""
    eff = arr * scale
    return jnp.where(eff < params.decay_to_zero, 0.0, eff)


def _reciprocal_view(
    edge_mask: jnp.ndarray, conns: jnp.ndarray, rev: jnp.ndarray,
    batch_factor: int = 1,
) -> jnp.ndarray:
    """view[q, j] = edge_mask[conns[q,j], rev[q,j]] — the counterpart edge's
    flag seen from my slot space. Because the reverse-slot map is an
    involution ((p,i) <-> (q,j)), a reciprocal *scatter* ("for every selected
    (p,i), mark (conns[p,i], rev[p,i])") is exactly this *gather*. One gather
    replaces the reference's GRAFT/PRUNE RPC round trips.

    Shape note (TPU): the naive 2-index-vector gather `m[conns, rev]` lowers
    to 4M random scalar loads (~45 ms at N=100k). Gathering whole neighbor
    ROWS (contiguous, embedding-style) and selecting the slot with a fused
    iota-compare is ~4x faster — see ops/pull.py for the measured numbers."""
    return reciprocal_pull_bool(edge_mask, conns, rev, batch_factor)


@partial(jax.jit, static_argnames=("params", "batch_factor"))
def heartbeat_step(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    batch_factor: int = 1,
    nbr_ok: jnp.ndarray | None = None,
    valid_pre: jnp.ndarray | None = None,
    decay_scales=None,
    deg_in: jnp.ndarray | None = None,
    edge_ok: jnp.ndarray | None = None,
):
    """`batch_factor`: width of any enclosing vmap (e.g. the topic axis of
    runtime/multitopic.py) so the pull memory dispatch sees the true
    allocation size (ops/pull.py). `nbr_ok`: optional precomputed neighbor
    alive&subscribed pull — pass it when alive/subscribed cannot change
    between steps (churn off) to hoist the pull out of a scan
    (run_heartbeats); XLA cannot prove loop-carried state invariant itself.
    `valid_pre`: the fully-assembled edge validity mask, hoisting the
    remaining per-step (N, C) conjunction too — the steady-state round is
    then one reduce plus cond probes.

    `decay_scales`: optional (fmd_scale, slow_scale) f32 scalars — the
    DEFERRED-decay protocol run_heartbeats uses. Score decay is a pure
    geometric shrink with a zero-cutoff, so across a scan it factors into
    one scalar per array: this step then touches NO (N, C) decay arrays
    (the caller materializes arr * scale with the cutoff once, after the
    scan), and any score read inside the cond branches applies the scale +
    cutoff on the fly — exactly the per-step-decayed value, because decay
    is monotone (once below decay_to_zero, always below).

    `deg_in`: optional carried (N,) mesh degree — the second scan-level
    protocol (requires `valid_pre`). The caller must have established the
    invariant mesh_mask ⊆ valid_pre (one AND before the scan); every
    branch write here re-ANDs with `valid`, so the invariant is
    preserved, the per-step (N, C) mesh-AND and degree reduce both
    disappear, and the degree is re-reduced only inside a cond when a
    branch actually changed the mesh. When given, the step returns
    (state, deg_out) instead of state.

    `edge_ok`: optional (N, C) per-edge availability mask ANDed into the
    validity conjunction — the fault-injection hook (ops/faults.py): a
    partitioned edge is connected but unusable, so it falls out of `valid`
    exactly like an edge to a dead peer. None keeps the default trace
    untouched (the same optional-arg contract as nbr_ok/valid_pre)."""
    if deg_in is not None and (
        valid_pre is None
        or edge_ok is not None
        or params.churn_down_per_hb > 0.0
        or params.churn_up_per_hb > 0.0
    ):
        # the carried-degree protocol only makes sense on top of the
        # hoisted validity mask with churn off; reject misuse loudly (the
        # degrees would silently count edges to dead/unsubscribed peers,
        # or the return arity would silently change under churn)
        raise ValueError("deg_in requires valid_pre, no edge_ok, and churn "
                         "off (run_heartbeats' churn-free scan protocol)")
    n, c = conns.shape
    key, k_graft, k_keep, k_churn_d, k_churn_u = jax.random.split(state.key, 5)
    t = state.t_ms

    # -- churn (failure injection; BASELINE config 4) ------------------------
    alive = state.alive
    if params.churn_down_per_hb > 0.0 or params.churn_up_per_hb > 0.0:
        dies = jax.random.uniform(k_churn_d, (n,)) < params.churn_down_per_hb
        revives = jax.random.uniform(k_churn_u, (n,)) < params.churn_up_per_hb
        alive = jnp.where(alive, ~dies, revives)
        nbr_ok = None   # alive just changed; precomputed masks are stale
        valid_pre = None
        # the warm-start carry measured arrival offsets on the OLD liveness
        # set — a revived peer's stale offset (or a died relay's reachability)
        # makes the re-based seed meaningless, so invalidate the whole carry
        # (disseminate's certificate would catch a bad seed anyway; this
        # keeps the next publish on the cheap no-rerun path)
        warm = jnp.full_like(state.warm_offset_ms, 3.4e38)
    else:
        warm = state.warm_offset_ms

    if valid_pre is not None:
        valid = valid_pre
    else:
        has_conn = conns >= 0
        if nbr_ok is None:
            # one pull for the conjunction (alive AND subscribed) — each pull
            # is a full row-gather pass, so fusing the two masks halves the
            # cost
            nbr_ok = neighbor_pull_bool(
                alive & state.subscribed, conns, rev, batch_factor)
        valid = has_conn & alive[:, None] & nbr_ok & state.subscribed[:, None]
    if edge_ok is not None:
        # fault injection: a partitioned edge is invalid for the round even
        # though both endpoints are alive; applied after valid_pre too, so
        # the fault scan can hoist the liveness conjunction and still mask
        valid = valid & edge_ok

    if deg_in is not None:
        # carried-degree protocol: mesh_mask ⊆ valid already (caller's
        # pre-scan AND + every branch write re-ANDing), so the per-step
        # mesh-AND and degree reduce are skipped outright
        mesh = state.mesh_mask
        deg = deg_in
    else:
        mesh = state.mesh_mask & valid  # drop edges to dead/unsubscribed
        deg = mesh.sum(axis=-1)

    def _score_now():
        if decay_scales is None:
            return state.score(params)
        # deferred decay: reconstruct this step's exact decayed view and
        # delegate the score formula to the one place it lives
        f_sc, s_sc = decay_scales
        return state.replace(
            fmd=_apply_decay(state.fmd, f_sc, params),
            slow_penalty=_apply_decay(state.slow_penalty, s_sc, params),
        ).score(params)

    # score() is only consumed inside the cond-gated graft/prune/og branches;
    # computing it lazily there keeps the steady-state step score-free. With
    # opportunistic grafting enabled the og block needs scores every step
    # anyway — compute once and share instead of once per branch.
    _og_enabled = params.opportunistic_graft_threshold > -9999.0
    _scores = _score_now() if _og_enabled else None

    def get_scores():
        return _scores if _scores is not None else _score_now()

    # -- GRAFT: |mesh| < D_low -> add random eligible peers up to D ----------
    # The whole selection (uniform draw + double argsort + reciprocal pull)
    # runs under a cond: at steady state every row sits in [D_low, D_high]
    # and the step skips straight through. Key consumption stays identical
    # either way (k_graft was split above).
    need = jnp.where(deg < params.d_low, params.d - deg, 0)

    zeros_n = jnp.zeros((n,), jnp.int32)

    def do_graft(mesh):
        eligible = (valid & ~mesh & (state.backoff_until <= t)
                    & (get_scores() >= 0.0))
        g_prio = jnp.where(eligible, jax.random.uniform(k_graft, (n, c)), BIG)
        grafted = (_ranks(g_prio) < need[:, None]) & eligible
        # GRAFT control msg: counterpart adds us to its mesh (handleGraft
        # accepts unless backed off; overflow is corrected at its own next
        # heartbeat). The reciprocal view IS the receive side — both
        # directions are counted per peer. The counter increments and the
        # refreshed degree are reduced INSIDE the branch: at steady state
        # the round pays no (N, C) reduce for them at all.
        graft_rx = _reciprocal_view(grafted, conns, rev, batch_factor)
        mesh = (mesh | grafted | graft_rx) & valid
        return (mesh, mesh.sum(axis=-1),
                grafted.sum(axis=-1, dtype=jnp.int32),
                graft_rx.sum(axis=-1, dtype=jnp.int32))

    mesh, deg2, graft_tx_inc, graft_rx_inc = jax.lax.cond(
        (need > 0).any(),
        do_graft,
        lambda m: (m, deg, zeros_n, zeros_n),
        mesh,
    )

    # -- PRUNE: |mesh| > D_high -> keep D (D_score best, >= D_out outbound) --
    # The whole selection (4 rank passes) plus the reciprocal pull runs under
    # a cond: at steady state no row exceeds D_high and the step skips it.
    over = deg2 > params.d_high

    def _prune_sel(mesh):
        rand_keep = jax.random.uniform(k_keep, (n, c))
        scores = get_scores()
        # rank by descending score (random tiebreak) among mesh members
        s_prio = jnp.where(mesh, -scores + 1e-3 * rand_keep, BIG)
        top_score = (_ranks(s_prio) < params.d_score) & mesh
        # at least D_out outbound among the kept set
        out_in_top = (top_score & out_mask).sum(axis=-1)
        need_out = jnp.clip(params.d_out - out_in_top, 0, params.d)
        o_prio = jnp.where(mesh & out_mask & ~top_score, rand_keep, BIG)
        keep_out = (_ranks(o_prio) < need_out[:, None]) & mesh & out_mask & ~top_score
        # random fill to exactly D
        base = top_score | keep_out
        need_fill = jnp.clip(params.d - base.sum(axis=-1), 0, params.d)
        f_prio = jnp.where(mesh & ~base, rand_keep, BIG)
        keep = base | ((_ranks(f_prio) < need_fill[:, None]) & mesh & ~base)
        pruned = mesh & ~keep & over[:, None]
        mesh = mesh & ~pruned
        # PRUNE control msg: counterpart drops us; backoff on both sides
        pruned_by_peer = _reciprocal_view(pruned, conns, rev, batch_factor)
        backoff = jnp.where(
            pruned | pruned_by_peer,
            t + params.prune_backoff_ms, state.backoff_until)
        return (mesh & ~pruned_by_peer, backoff,
                pruned.sum(axis=-1, dtype=jnp.int32),
                pruned_by_peer.sum(axis=-1, dtype=jnp.int32),
                pruned_by_peer)

    pruned_rx = None
    if params.px:
        # PX needs the received-PRUNE edge set out of the branch; the extra
        # output exists only on the opt-in trace (ops/repair.py)
        mesh, backoff, prune_tx_inc, prune_rx_inc, pruned_rx = jax.lax.cond(
            over.any(),
            _prune_sel,
            lambda m: (m, state.backoff_until, zeros_n, zeros_n,
                       jnp.zeros((n, c), dtype=bool)),
            mesh,
        )
    else:
        mesh, backoff, prune_tx_inc, prune_rx_inc = jax.lax.cond(
            over.any(),
            lambda m: _prune_sel(m)[:4],
            lambda m: (m, state.backoff_until, zeros_n, zeros_n),
            mesh,
        )

    # -- score eviction (mesh repair; opt-in via params.evict) ---------------
    # v1.1 mesh maintenance also drops members whose score sank below a
    # floor, with PRUNE + backoff on both sides (go-libp2p-pubsub prunes
    # negative-score peers before rebalancing). Statically gated so the
    # default step carries none of it; inside the gate a separate lax.cond
    # keeps the healthy steady state (nobody under the floor) probe-cheap.
    # Reciprocity reuses _reciprocal_view — identical PRUNE semantics to
    # _prune_sel. The predicate pays one score materialization per step;
    # that is the documented cost of arming eviction.
    ev_tx_inc = ev_rx_inc = None
    evict_fired = None
    ev_rx_edges = None
    if params.evict:
        ev_cand = mesh & (get_scores() < params.eviction_threshold)
        evict_fired = ev_cand.any()

        def do_evict(mesh, backoff):
            ev_rx = _reciprocal_view(ev_cand, conns, rev, batch_factor)
            new_backoff = jnp.where(
                ev_cand | ev_rx, t + params.prune_backoff_ms, backoff)
            return (mesh & ~ev_cand & ~ev_rx, new_backoff,
                    ev_cand.sum(axis=-1, dtype=jnp.int32),
                    ev_rx.sum(axis=-1, dtype=jnp.int32),
                    ev_rx)

        mesh, backoff, ev_tx_inc, ev_rx_inc, ev_rx_edges = jax.lax.cond(
            evict_fired,
            do_evict,
            lambda m, b: (m, b, zeros_n, zeros_n,
                          jnp.zeros((n, c), dtype=bool)),
            mesh, backoff,
        )

    # -- PX on PRUNE (mesh repair; opt-in via params.px) ---------------------
    # Every PRUNE (degree rebalance or eviction) carries up to px_count
    # candidate peer ids: the pruner's best-scored valid neighbors ("honest"
    # proxied by score >= 0 — penalized/graylisted peers are never
    # advertised). The prunee stores them in its px_pool; acting on them
    # (graft / dial) is the repair controller's job next heartbeat
    # (ops/repair.py repair_round). Deterministic slot-index tiebreak: no
    # PRNG is consumed, keeping the default key schedule untouched.
    px_pool = None
    if params.px:
        got_pruned = pruned_rx
        if ev_rx_edges is not None:
            got_pruned = got_pruned | ev_rx_edges

        def do_px(pool):
            scores = get_scores()
            elig = valid & (scores >= 0.0)
            prio = (jnp.where(elig, -scores, BIG)
                    + 1e-4 * jnp.arange(c, dtype=jnp.float32))
            w = min(PX_POOL_WIDTH, c)
            order = jnp.argsort(prio, axis=-1)[:, :w]
            take_ok = (jnp.take_along_axis(elig, order, axis=-1)
                       & (jnp.arange(w) < params.px_count))
            cand = jnp.where(
                take_ok, jnp.take_along_axis(conns, order, axis=-1), -1)
            if w < PX_POOL_WIDTH:
                cand = jnp.pad(cand, ((0, 0), (0, PX_POOL_WIDTH - w)),
                               constant_values=-1)
            # the prunee reads the advert off ONE pruning edge (the lowest
            # pruning slot) — one row-gather through the involution, same
            # shape economics as _reciprocal_view
            got = got_pruned.any(axis=-1)
            i0 = jnp.argmax(got_pruned, axis=-1)
            pruner = jnp.take_along_axis(conns, i0[:, None], axis=1)[:, 0]
            advert = cand[jnp.clip(pruner, 0)]
            advert = jnp.where(
                advert == jnp.arange(n, dtype=jnp.int32)[:, None], -1, advert)
            return jnp.where(got[:, None], advert, pool)

        px_pool = jax.lax.cond(
            got_pruned.any(), do_px, lambda p: p, state.px_pool)

    # -- opportunistic grafting (v1.1, main.nim:292): when the MEDIAN mesh
    # score sinks below the threshold, graft up to 2 peers scoring above the
    # median (escape hatch from a low-quality mesh). Static-gated: at the
    # disabled default (-10000) the sort never enters the compiled step.
    og_tx_inc = zeros_n
    og_rx_inc = zeros_n
    if params.opportunistic_graft_threshold > -9999.0:
        scores = get_scores()
        deg3 = mesh.sum(axis=-1)
        msort = jnp.sort(jnp.where(mesh, scores, BIG), axis=-1)
        # upper median (sorted[len/2]) — matches the libp2p implementations
        k_med = jnp.clip(deg3 // 2, 0, c - 1)
        median = jnp.take_along_axis(msort, k_med[:, None], axis=-1)[:, 0]
        low = (median < params.opportunistic_graft_threshold) & (deg3 > 0)
        og_elig = (valid & ~mesh & (backoff <= t)
                   & (scores > median[:, None]) & low[:, None])
        og_prio = jnp.where(og_elig, -scores, BIG)  # best scores first
        og = (_ranks(og_prio) < 2) & og_elig
        # same steady-state economics as graft/prune: the reciprocal pull
        # and the counter reduces only run when something actually grafted
        def do_og(m):
            rx = _reciprocal_view(og, conns, rev, batch_factor)
            return ((m | og | rx) & valid,
                    og.sum(axis=-1, dtype=jnp.int32),
                    rx.sum(axis=-1, dtype=jnp.int32))

        mesh, og_tx_inc, og_rx_inc = jax.lax.cond(
            og.any(),
            do_og,
            lambda m: (m, zeros_n, zeros_n),
            mesh,
        )

    # -- score decay (decayInterval == heartbeat here; main.nim:272-273) -----
    if decay_scales is not None:
        # deferred: the scan carries the scalar scales; arrays untouched
        fmd, slow = state.fmd, state.slow_penalty
    else:
        # gated: once everything decayed to zero (no recent messages) the
        # two (N, C) rewrite passes per step are skipped
        def do_decay(fmd, slow):
            return (_apply_decay(fmd, params.fmd_decay, params),
                    _apply_decay(slow, params.slow_decay, params))

        fmd, slow = jax.lax.cond(
            # one fused (N, C) reduce for the predicate, not one per array
            ((state.fmd > 0) | (state.slow_penalty > 0)).any(),
            do_decay,
            lambda f, s: (f, s),
            state.fmd, state.slow_penalty,
        )

    # -- fanout expiry (v1.1 fanoutTTL): a fanout set whose owner hasn't
    # fanout-published within the TTL is dropped wholesale (nim-libp2p
    # dropFanoutPeers). Gated on the (N,) expiry stamps — nonzero only for
    # peers that ever fanout-published — so runs with no fanout publishers
    # pay an (N,) reduce, not an (N, C) one.
    fanout = jax.lax.cond(
        (state.fanout_expire > 0.0).any(),
        lambda fm: fm & (t < state.fanout_expire)[:, None],
        lambda fm: fm,
        state.fanout_mask,
    )

    prunes_new = state.prunes + prune_tx_inc
    prunes_rx_new = state.prunes_rx + prune_rx_inc
    repair_extra = {}
    if params.evict:
        # an eviction IS a PRUNE control message; count it in both ledgers
        prunes_new = prunes_new + ev_tx_inc
        prunes_rx_new = prunes_rx_new + ev_rx_inc
        repair_extra["evictions"] = state.evictions + ev_tx_inc
    if params.px:
        repair_extra["px_pool"] = px_pool
    new_state = state.replace(
        mesh_mask=mesh,
        fanout_mask=fanout,
        backoff_until=backoff,
        fmd=fmd,
        slow_penalty=slow,
        alive=alive,
        warm_offset_ms=warm,
        t_ms=t + params.heartbeat_ms,
        key=key,
        grafts=state.grafts + graft_tx_inc + og_tx_inc,
        grafts_rx=state.grafts_rx + graft_rx_inc + og_rx_inc,
        prunes=prunes_new,
        prunes_rx=prunes_rx_new,
        **repair_extra,
    )
    if deg_in is None:
        return new_state
    # carried degree: re-reduce only if some branch actually touched the
    # mesh this step — the steady-state round stays free of (N, C) reduces
    fired = (need > 0).any() | over.any()
    if params.opportunistic_graft_threshold > -9999.0:
        fired = fired | og.any()
    if params.evict:
        fired = fired | evict_fired
    deg_out = jax.lax.cond(
        fired, lambda m: m.sum(axis=-1), lambda m: deg_in, mesh)
    return new_state, deg_out


def run_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    steps: int,
) -> SimState:
    """lax.scan over heartbeat rounds — simulated time scales in rounds with
    no host sync (the reference's 'long simulated time' axis, SURVEY.md §5).

    The jitted scan is `_run_heartbeats`; this boundary strips the 5
    mesh-repair leaves from the carry when no repair knob is armed — they
    are provably untouched then, and carrying them cost the r05 bench ~6
    passthrough buffers per segment (ops/state.py strip_repair). NOT
    donated: callers (bench.py, tests) re-run segments from a kept state.
    Jitted with static `steps` so repeated same-length segments (the
    simulator's inter-message gaps) hit the compile cache."""
    if repair_inert(params):
        state, saved = strip_repair(state)
        out = _run_heartbeats(state, conns, rev, out_mask, params, steps)
        return restore_repair(out, saved)
    return _run_heartbeats(state, conns, rev, out_mask, params, steps)


@partial(jax.jit, static_argnames=("params", "steps"))
def _run_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    steps: int,
) -> SimState:

    nbr_ok = None
    valid_pre = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        # alive/subscribed are invariant across the scan without churn, so
        # the neighbor pull — a full row-gather pass — hoists out of the
        # loop, and so does the whole edge-validity conjunction
        nbr_ok = neighbor_pull_bool(state.alive & state.subscribed, conns, rev)
        valid_pre = ((conns >= 0) & state.alive[:, None] & nbr_ok
                     & state.subscribed[:, None])

    one = jnp.float32(1.0)
    if valid_pre is not None:
        # carried-degree protocol: establish mesh_mask ⊆ valid ONCE (the
        # AND every step used to apply), then the steady-state round pays
        # no (N, C) mesh-AND or degree reduce at all
        mesh0 = state.mesh_mask & valid_pre
        state = state.replace(mesh_mask=mesh0)

        def body(carry, _):
            s, deg, f_sc, s_sc = carry
            s, deg = heartbeat_step(
                s, conns, rev, out_mask, params, nbr_ok=nbr_ok,
                valid_pre=valid_pre, decay_scales=(f_sc, s_sc), deg_in=deg)
            return (s, deg, f_sc * params.fmd_decay,
                    s_sc * params.slow_decay), None

        (state, _, f_sc, s_sc), _ = jax.lax.scan(
            body, (state, mesh0.sum(axis=-1), one, one), None, length=steps)
    else:
        def body(carry, _):
            s, f_sc, s_sc = carry
            s = heartbeat_step(
                s, conns, rev, out_mask, params, nbr_ok=nbr_ok,
                valid_pre=valid_pre, decay_scales=(f_sc, s_sc))
            # end-of-round decay, factored to two scalar multiplies
            return (s, f_sc * params.fmd_decay,
                    s_sc * params.slow_decay), None

        (state, f_sc, s_sc), _ = jax.lax.scan(
            body, (state, one, one), None, length=steps)
    # materialize the deferred decay ONCE per scan (vs two (N, C) passes
    # plus a predicate reduce per round): exact, because geometric decay
    # with a monotone zero-cutoff commutes with deferral
    return state.replace(
        fmd=_apply_decay(state.fmd, f_sc, params),
        slow_penalty=_apply_decay(state.slow_penalty, s_sc, params),
    )
