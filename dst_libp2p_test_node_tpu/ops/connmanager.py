"""Connection-manager stress workload: hub watermark dynamics as a jit scan.

The reference connmanager node (nim-test-node/connmanager/{main,env}.nim)
stresses nim-libp2p's ConnManager (7cc4280e connmanager-logging branch): a
hub with `withWatermark(lowWater, highWater, gracePeriod, silencePeriod)`
trimming and an optional hard cap (maxConnections, main.nim:54-55), protected
peers (connManager.protect, main.nim:59-60), hub-to-hub full mesh
(main.nim:80-91), and spoke peers with three reconnect strategies
(main.nim:115-139):

  ReconnectNone        dial each hub once, then idle
  ReconnectAggressive  every 1 s: if outbound conns < |hubs|, redial all hubs
  ReconnectBeforeGrace dial, wait reconnectInterval, disconnect all, repeat —
                       deliberately staying inside every hub's grace window
                       ("Cycled connection (grace abuse)", main.nim:132)

TPU-native design: connection state is an (H, M) edge matrix (hubs x peers)
of booleans + connect timestamps; one `lax.scan` step = one second. Each step
applies, in order: peer dial decisions (per-role masks), the hard cap
(capacity-ranked accept), and — on silence-period ticks — watermark trimming:
if a hub's count exceeds highWater, disconnect down to lowWater, sparing
protected peers and connections younger than gracePeriod, evicting the
OLDEST eligible connections first (the manager trims long-lived excess while
the grace window shields fresh dials — the behavior the grace-abuse strategy
exploits). The scan emits a per-tick connection-count trace, the workload's
primary measured output.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

RECONNECT_NONE = 0
RECONNECT_AGGRESSIVE = 1
RECONNECT_BEFORE_GRACE = 2

BIG = jnp.float32(1e30)


@dataclass(frozen=True)
class ConnManagerParams:
    """Static workload parameters (hub + peer env surface, env.nim:14-105)."""

    n_hubs: int = 1               # NUM_HUBS
    n_peers: int = 40
    low_water: int = 10           # WATERMARK_LOW
    high_water: int = 20          # WATERMARK_HIGH
    grace_period_s: int = 0       # WATERMARK_GRACE_PERIOD_S
    silence_period_s: int = 2     # WATERMARK_SILENCE_PERIOD_S
    max_connections: int = 0      # MAX_CONNECTIONS; 0 = no hard cap
    reconnect_interval_s: int = 55  # RECONNECT_INTERVAL_S

    def validate(self) -> None:
        if not (0 < self.low_water <= self.high_water):
            raise ValueError("require 0 < low_water <= high_water")
        if self.silence_period_s < 1:
            raise ValueError("silence_period_s must be >= 1")
        if self.n_hubs < 1 or self.n_peers < 1:
            raise ValueError("need at least one hub and one peer")


@struct.dataclass
class ConnState:
    """Device-side hub-spoke connection state."""

    conn: jnp.ndarray          # (H, M) bool — peer-to-hub connection up
    since_ms: jnp.ndarray      # (H, M) float32 — connect timestamp
    hub_conn: jnp.ndarray      # (H, H) bool — hub-to-hub mesh
    t_ms: jnp.ndarray          # () float32
    key: jnp.ndarray
    # counters (the connmanager-logging branch's log-derived measurables)
    dials: jnp.ndarray         # () int32 successful connects
    rejected: jnp.ndarray      # () int32 dials refused by the hard cap
    trims: jnp.ndarray         # () int32 watermark disconnects
    cycles: jnp.ndarray        # () int32 grace-abuse cycle disconnects


def init_conn_state(params: ConnManagerParams, seed: int = 0) -> ConnState:
    h, m = params.n_hubs, params.n_peers
    return ConnState(
        conn=jnp.zeros((h, m), bool),
        since_ms=jnp.zeros((h, m), jnp.float32),
        hub_conn=(~jnp.eye(h, dtype=bool)) if h > 1 else jnp.zeros((h, h), bool),
        t_ms=jnp.asarray(0.0, jnp.float32),
        key=jax.random.PRNGKey(seed),
        dials=jnp.asarray(0, jnp.int32),
        rejected=jnp.asarray(0, jnp.int32),
        trims=jnp.asarray(0, jnp.int32),
        cycles=jnp.asarray(0, jnp.int32),
    )


def _ranks(priority: jnp.ndarray) -> jnp.ndarray:
    return jnp.argsort(jnp.argsort(priority, axis=-1), axis=-1)


@partial(jax.jit, static_argnames=("params",))
def conn_step(
    state: ConnState,
    reconnect_mode: jnp.ndarray,   # (M,) int32 per-peer strategy
    dial_out: jnp.ndarray,         # (M,) bool — DIAL_OUT
    protected: jnp.ndarray,        # (M,) bool — PROTECTED_PEERS
    params: ConnManagerParams,
) -> ConnState:
    """One 1-second tick of the hub/peer programs."""
    h, m = state.conn.shape
    t = state.t_ms + 1000.0
    key, k_dial = jax.random.split(state.key)
    conn, since = state.conn, state.since_ms
    cycles = state.cycles

    # -- peer programs (main.nim:115-139) ------------------------------------
    # before_grace: on each reconnectInterval boundary, drop everything...
    tick = jnp.int32(t / 1000.0)
    cycle_now = (tick % params.reconnect_interval_s == 0) & (
        reconnect_mode == RECONNECT_BEFORE_GRACE
    )
    dropped = conn & cycle_now[None, :]
    cycles = cycles + dropped.sum(dtype=jnp.int32)
    conn = conn & ~cycle_now[None, :]

    # dial decisions: aggressive redials every tick while any hub is missing;
    # none/before_grace dial whenever currently unconnected (none only ever
    # fires at t=0 or after a trim with no retry budget left -> model the
    # 10-attempt backoff envelope as one-shot: dial only if never connected)
    missing = ~conn                               # (H, M)
    aggressive = (reconnect_mode == RECONNECT_AGGRESSIVE) & (
        conn.sum(axis=0) < h
    )
    first_dial = (since.max(axis=0) == 0.0) & ~conn.any(axis=0)
    cycler = reconnect_mode == RECONNECT_BEFORE_GRACE
    wants = dial_out & (aggressive | first_dial | (cycler & cycle_now))
    dialing = missing & wants[None, :]

    # -- hard cap (MAX_CONNECTIONS semaphore, main.nim:54-55) ----------------
    if params.max_connections > 0:
        room = params.max_connections - conn.sum(axis=-1)
        order = _ranks(jnp.where(dialing, jax.random.uniform(k_dial, (h, m)), BIG))
        accepted = dialing & (order < room[:, None])
        rejected = (dialing & ~accepted).sum(dtype=jnp.int32)
    else:
        accepted = dialing
        rejected = jnp.int32(0)

    since = jnp.where(accepted & ~conn, t, since)
    conn = conn | accepted
    dials = state.dials + accepted.sum(dtype=jnp.int32)

    # -- hub watermark trim, every silencePeriod ticks -----------------------
    trim_now = tick % params.silence_period_s == 0
    count = conn.sum(axis=-1)                     # (H,)
    over = (count > params.high_water) & trim_now
    excess = jnp.where(over, count - params.low_water, 0)
    age_ms = t - since
    in_grace = age_ms < params.grace_period_s * 1000.0
    evictable = conn & ~protected[None, :] & ~in_grace
    # oldest eligible first: rank by descending age
    prio = jnp.where(evictable, -age_ms, BIG)
    evict = (_ranks(prio) < excess[:, None]) & evictable
    trims = state.trims + evict.sum(dtype=jnp.int32)
    conn = conn & ~evict

    return state.replace(
        conn=conn,
        since_ms=since,
        t_ms=t,
        key=key,
        dials=dials,
        rejected=state.rejected + rejected,
        trims=trims,
        cycles=cycles,
    )


@partial(jax.jit, static_argnames=("params", "steps"))
def run_conn_steps(
    state: ConnState,
    reconnect_mode: jnp.ndarray,
    dial_out: jnp.ndarray,
    protected: jnp.ndarray,
    params: ConnManagerParams,
    steps: int,
):
    """Scan `steps` seconds; returns (state, per-tick hub conn counts (T, H))
    — the connection-count time series the reference reads off its metrics."""

    def body(s, _):
        s = conn_step(s, reconnect_mode, dial_out, protected, params)
        # a hub's connection count includes its hub-to-hub mesh edges
        # (main.nim:80-91 dials every other hub replica); the mesh is
        # infrastructure the hubs keep alive, so it rides outside the
        # spoke-trim dynamics but inside the reported count
        total = (s.conn.sum(axis=-1) + s.hub_conn.sum(axis=-1))
        return s, total.astype(jnp.int32)

    return jax.lax.scan(body, state, None, length=steps)


# ---------------------------------------------------------------- experiment


@dataclass
class ConnManagerConfig:
    """Whole-experiment shape: the reference deploys role-per-pod via
    NODE_ROLE/RECONNECT env (env.nim:39-105); here the simulator owns all
    roles, with peer counts per strategy."""

    params: ConnManagerParams = field(default_factory=ConnManagerParams)
    n_none: int = 20
    n_aggressive: int = 10
    n_before_grace: int = 10
    n_protected: int = 0          # first peers of the none-group, protected
    duration_s: int = 120
    seed: int = 0

    def roles(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        m = self.params.n_peers
        assert self.n_none + self.n_aggressive + self.n_before_grace == m
        mode = np.concatenate([
            np.full(self.n_none, RECONNECT_NONE),
            np.full(self.n_aggressive, RECONNECT_AGGRESSIVE),
            np.full(self.n_before_grace, RECONNECT_BEFORE_GRACE),
        ]).astype(np.int32)
        dial_out = np.ones(m, bool)
        protected = np.zeros(m, bool)
        protected[: self.n_protected] = True
        return mode, dial_out, protected


@dataclass
class ConnManagerSummary:
    mean_conns: float
    max_conns: int
    min_conns_after_warm: int
    dials: int
    rejected: int
    trims: int
    cycles: int
    trace: np.ndarray            # (T, H) per-tick counts

    def report(self) -> str:
        return "\n".join([
            "ConnManager summary",
            f"Hub connections: mean {self.mean_conns:.1f} max {self.max_conns} "
            f"min-after-warmup {self.min_conns_after_warm}",
            f"Dials accepted: {self.dials}  rejected by cap: {self.rejected}",
            f"Watermark trims: {self.trims}",
            f"Grace-abuse cycles: {self.cycles}",
        ])


def run_connmanager(cfg: ConnManagerConfig) -> tuple[ConnManagerSummary, ConnState]:
    cfg.params.validate()
    if cfg.duration_s < 1:
        raise ValueError("duration_s must be >= 1")
    mode, dial_out, protected = cfg.roles()
    state = init_conn_state(cfg.params, seed=cfg.seed)
    state, trace = run_conn_steps(
        state, jnp.asarray(mode), jnp.asarray(dial_out), jnp.asarray(protected),
        cfg.params, cfg.duration_s,
    )
    tr = np.asarray(trace)
    warm = min(5, len(tr) - 1)
    summary = ConnManagerSummary(
        mean_conns=float(tr.mean()),
        max_conns=int(tr.max()),
        min_conns_after_warm=int(tr[warm:].min()),
        dials=int(state.dials),
        rejected=int(state.rejected),
        trims=int(state.trims),
        cycles=int(state.cycles),
        trace=tr,
    )
    return summary, state


def config_from_env() -> ConnManagerConfig:
    """WATERMARK_*/MAX_CONNECTIONS/RECONNECT* env surface (env.nim:39-105)."""
    from ..config.env import env_int, env_str

    n_none = env_int("CONNMGR_PEERS_NONE", 20)
    n_agg = env_int("CONNMGR_PEERS_AGGRESSIVE", 10)
    n_bg = env_int("CONNMGR_PEERS_BEFORE_GRACE", 10)
    params = ConnManagerParams(
        n_hubs=env_int("NUM_HUBS", 1),
        n_peers=n_none + n_agg + n_bg,
        low_water=env_int("WATERMARK_LOW", 10),
        high_water=env_int("WATERMARK_HIGH", 20),
        grace_period_s=env_int("WATERMARK_GRACE_PERIOD_S", 0),
        silence_period_s=env_int("WATERMARK_SILENCE_PERIOD_S", 2),
        max_connections=env_int("MAX_CONNECTIONS", 0),
        reconnect_interval_s=env_int("RECONNECT_INTERVAL_S", 55),
    )
    n_protected = len([s for s in env_str("PROTECTED_PEERS", "").split(",")
                       if s.strip()])
    return ConnManagerConfig(
        params=params,
        n_none=n_none,
        n_aggressive=n_agg,
        n_before_grace=n_bg,
        n_protected=n_protected,
        duration_s=env_int("CONNMGR_DURATION_S", 120),
        seed=env_int("SEED", 0),
    )
