"""Mesh repair: score eviction, PX-on-PRUNE, and re-dial recovery.

GossipSub v1.1's resilience story is not just that badly-scored peers stop
being *accepted* — the mesh actively heals (arXiv:2007.02754 §2; the ACL2s
formalization arXiv:2311.08859 treats the PRUNE/PX/backoff machine as the
correctness-critical core):

  eviction   mesh maintenance PRUNEs members whose score sank below a floor,
             with backoff on both sides (the opt-in `params.evict` lax.cond
             branch in ops/heartbeat.py).
  PX         a PRUNE carries peer-exchange candidates — the pruner's
             best-scored neighbors — which the prunee may graft or dial
             (the opt-in `params.px` capture branch in ops/heartbeat.py
             writes SimState.px_pool; `repair_round` here acts on it).
  re-dial    a peer starved below D_low for `redial_patience` heartbeats
             dials its way back in: PX pool first, then the ambient
             known-peer table (modeled as a uniform random peer — every
             reference node keeps a peer store / bootstrap list).

The dial controller makes the CONNECTION GRAPH dynamic — the one thing the
engine's involution substrate (ops/graph.py) treats as an epoch constant.
The contract that keeps this sound:

  * new edges only ever fill never-used padding slots (conns == -1); the
    reverse-slot involution is extended functionally in the same round
    (conns/rev/out_mask travel in the scan carry, never mutated in place);
  * at most ONE dial per peer per heartbeat, and an acceptor takes at most
    one inbound dial per round (lowest dialer id wins; a dialing peer does
    not accept) — collision-free fixed-shape scatters, no retry loops;
  * any committed dial invalidates the warm-start carry wholesale
    (SimState.warm_offset_ms := INF — the same invalidation contract as
    churn: the offsets were measured on the old reachability graph), and
    the host must re-derive every hoisted per-edge table before the next
    publish (Simulator.rebind_graph: valid_edge, lat_edge/loss_edge,
    answer tables all index the mutated conns/rev).

Adversary models. The STATIC runners (`run_recovery_heartbeats`,
`run_dht_recovery_heartbeats`) pass actor=~attacker: attackers do NOT run
the repair controller to worm back into the mesh after eviction, and on
the DHT leg their identities refuse inbound dials (refuse=attacker) — the
weakest opponent. `run_adaptive_recovery_heartbeats` is the arms-race
runner (ops/adversary.AdaptivePolicy): with slot_race armed the attacker
cohort runs the dial controller too AND accepts inbound dials (a sybil
that wants your slot completes the handshake), its controller re-grafts
at backoff expiry and re-poisons the PX pool after every repair pass, so
the candidate lattice honest repair draws from is contested every round.
Disabled, it literally delegates to the static runner (same jit cache
entry, bit-identical, zero extra PRNG).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .adversary import (AdversaryParams, adaptive_round, attack_observables)
from .heartbeat import heartbeat_step
from .state import (AdaptiveCtrl, SimParams, SimState, init_adaptive_ctrl)

INF = jnp.float32(3.4e38)


@dataclass(frozen=True)
class RepairParams:
    """The repair knobs as a standalone (hashable) config surface.

    These mirror the SimParams fields one-to-one; `apply` folds them into a
    SimParams so the campaign/CLI can arm repair on an existing experiment
    without re-deriving the whole parameter set. Defaults are all OFF —
    RepairParams().apply(p) == p and the compiled paths stay bit-identical
    to the repair-free engine."""

    evict: bool = False
    eviction_threshold: float = -50.0
    px: bool = False
    px_count: int = 6
    redial: bool = False
    redial_patience: int = 3

    @property
    def enabled(self) -> bool:
        return self.evict or self.px or self.redial

    def validate(self) -> None:
        if self.eviction_threshold > 0:
            raise ValueError("eviction_threshold must be <= 0")
        if self.px_count < 1:
            raise ValueError("px_count must be >= 1")
        if self.redial_patience < 1:
            raise ValueError("redial_patience must be >= 1")

    def apply(self, params: SimParams) -> SimParams:
        out = dataclasses.replace(
            params,
            evict=self.evict,
            eviction_threshold=self.eviction_threshold,
            px=self.px,
            px_count=self.px_count,
            redial=self.redial,
            redial_patience=self.redial_patience,
        )
        out.validate()
        return out


def repair_round(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    actor: jnp.ndarray | None = None,
    batch_factor: int = 1,
    dht_pool: jnp.ndarray | None = None,
    refuse: jnp.ndarray | None = None,
):
    """One round of the repair controller, applied AFTER heartbeat_step.

    Returns (state, conns, rev, out_mask) — the graph arrays are part of the
    result because committed dials extend the involution. `actor`: (N,) bool
    mask of peers that RUN the controller (default all); non-actors still
    accept inbound dials (acceptance is passive — a socket, not a policy).

    Per acting peer and round, at most one action:
      graft  the first plausible PX candidate that is already connected
             (subject to both sides' backoff, degree need, and score >= 0 —
             exactly handleGraft's acceptance), or
      dial   an unconnected candidate — PX pool first, else (re-dial
             trigger) a uniform random known peer — filling one free slot
             on each side and grafting the fresh edge (score 0, no backoff).

    `dht_pool`: optional (N, K) discovery shortlist (a FIND_NODE self-lookup,
    ops/dht_adversary.dht_repair_pool) that REPLACES the uniform-random
    fallback as the re-dial candidate source — the candidate-source lattice
    becomes PX pool -> DHT shortlist -> nothing. The examined DHT entry is
    consumed success-or-fail (like the PX pool) so a dead or refusing
    candidate cannot wedge the controller, and the updated pool is returned
    as a fifth result. `refuse`: optional (N,) bool of peers that never
    accept an inbound dial (sybil identities are not connectable
    endpoints); a starved peer whose every candidate refuses keeps its
    starve_hb counter growing instead of wedging. Both are python-level
    (None compiles the original program — bit-identical, same key
    schedule).

    The whole action machinery runs under one lax.cond: a healthy network
    (nobody starved, no PX pending) pays only the trigger probes."""
    n, c = conns.shape
    me = jnp.arange(n, dtype=jnp.int32)
    iota_c = jnp.arange(c, dtype=jnp.int32)
    t = state.t_ms
    alive_sub = state.alive & state.subscribed
    act = alive_sub if actor is None else (actor & alive_sub)

    deg = state.mesh_mask.sum(axis=-1)

    # -- starvation counter (re-dial trigger) --------------------------------
    if params.redial:
        starve = jnp.where(act & (deg < params.d_low), state.starve_hb + 1, 0)
    else:
        starve = state.starve_hb

    key, k_dial = jax.random.split(state.key)

    # -- candidate selection (cheap, outside the cond: it IS the trigger) ----
    pool = state.px_pool
    pool_c = jnp.clip(pool, 0)
    cand_ok = (pool >= 0) & (pool != me[:, None]) & alive_sub[pool_c]
    has_cand = cand_ok.any(axis=-1)
    k0 = jnp.argmax(cand_ok, axis=-1)
    cand = jnp.take_along_axis(pool, k0[:, None], axis=1)[:, 0]

    # ambient known-peer table: one uniform draw over [0, n) \ {me}
    r = jax.random.randint(k_dial, (n,), 0, n - 1, dtype=jnp.int32)
    r = jnp.where(r >= me, r + 1, r)

    px_want = jnp.zeros((n,), dtype=bool)
    redial_want = jnp.zeros((n,), dtype=bool)
    if params.px:
        px_want = act & (deg < params.d) & has_cand
    if params.redial:
        redial_want = act & (starve >= params.redial_patience)
    use_px = px_want | (redial_want & has_cand)
    if dht_pool is None:
        use_rand = redial_want & ~has_cand & alive_sub[r]
        want = use_px | use_rand
        tgt = jnp.where(use_px, cand, jnp.where(use_rand, r, -1))
    else:
        # discovery-backed re-dial: the DHT shortlist replaces the uniform
        # random fallback entirely — a poisoned lookup measurably starves
        # the controller instead of being papered over by ambient luck
        d_ok = ((dht_pool >= 0) & (dht_pool != me[:, None])
                & alive_sub[jnp.clip(dht_pool, 0)])
        has_dcand = d_ok.any(axis=-1)
        dk0 = jnp.argmax(d_ok, axis=-1)
        dcand = jnp.take_along_axis(dht_pool, dk0[:, None], axis=1)[:, 0]
        use_dht = redial_want & ~has_cand & has_dcand
        want = use_px | use_dht
        tgt = jnp.where(use_px, cand, jnp.where(use_dht, dcand, -1))
    tgt_c = jnp.clip(tgt, 0)

    def _fire(_):
        hit = (conns == tgt_c[:, None]) & want[:, None]
        connected = hit.any(axis=-1)
        slot_a = jnp.argmax(hit, axis=-1)

        # ---- path A: candidate already connected -> plain GRAFT ----------
        sc = state.score(params)
        take = lambda a: jnp.take_along_axis(a, slot_a[:, None], axis=1)[:, 0]
        j_a = take(rev)
        my_ok = ((take(state.backoff_until) <= t)
                 & (take(sc) >= 0.0) & ~take(state.mesh_mask))
        graft_a = (want & connected & my_ok
                   & (state.backoff_until[tgt_c, j_a] <= t)
                   & (sc[tgt_c, j_a] >= 0.0))
        mesh = state.mesh_mask | (
            graft_a[:, None] & (iota_c[None, :] == slot_a[:, None]))
        mesh = mesh.at[tgt_c, j_a].max(graft_a)

        # ---- path B: unconnected -> dial into a free padding slot --------
        has_free = (conns < 0).any(axis=-1)
        free_slot = jnp.argmax(conns < 0, axis=-1).astype(jnp.int32)
        dial_try = want & ~connected & has_free
        # target-side screening: free slot, alive, not itself dialing (a
        # dialer never accepts in the same round — breaks the mutual-dial
        # double-edge race deterministically)
        attempt = dial_try & has_free[tgt_c] & alive_sub[tgt_c] & ~dial_try[tgt_c]
        if refuse is not None:
            # sybil identities never complete a handshake: the dial is
            # attempted (and the candidate consumed) but cannot commit
            attempt = attempt & ~refuse[tgt_c]
        # one inbound dial per acceptor per round: lowest dialer id wins
        winner = jnp.full((n,), n, dtype=jnp.int32).at[
            jnp.where(attempt, tgt_c, 0)].min(jnp.where(attempt, me, n))
        committed = attempt & (winner[tgt_c] == me)
        accepted = winner < n
        dialer = jnp.where(accepted, winner, 0)

        my_hot = committed[:, None] & (iota_c[None, :] == free_slot[:, None])
        acc_hot = accepted[:, None] & (iota_c[None, :] == free_slot[:, None])
        j_t = free_slot[tgt_c]       # my rev entry = the target's free slot
        i_d = free_slot[dialer]      # acceptor's rev entry = dialer's slot
        new_conns = jnp.where(my_hot, tgt_c[:, None], conns)
        new_conns = jnp.where(acc_hot, dialer[:, None], new_conns)
        new_rev = jnp.where(my_hot, j_t[:, None], rev)
        new_rev = jnp.where(acc_hot, i_d[:, None], new_rev)
        new_out = out_mask | my_hot  # the dialer side is the outbound one

        # fresh edge: scrub per-edge state (padding slots are zero already —
        # defense in depth) and graft both sides (score 0, no backoff: this
        # is exactly the PX-graft the prunee was promised)
        hot = my_hot | acc_hot
        mesh = mesh | hot
        backoff = jnp.where(hot, 0.0, state.backoff_until)
        fmd = jnp.where(hot, 0.0, state.fmd)
        slow = jnp.where(hot, 0.0, state.slow_penalty)

        # a committed dial changes the reachability graph the warm-start
        # offsets were measured on: invalidate the whole carry (the same
        # contract as churn, ops/heartbeat.py)
        warm = jnp.where(committed.any(),
                         jnp.full_like(state.warm_offset_ms, 3.4e38),
                         state.warm_offset_ms)

        i32 = jnp.int32
        grafts = state.grafts + (graft_a | committed).astype(i32)
        grafts_rx = state.grafts_rx.at[
            jnp.where(graft_a, tgt_c, 0)].add(graft_a.astype(i32))
        grafts_rx = grafts_rx + accepted.astype(i32)
        px_grafts = state.px_grafts + (
            graft_a | (committed & use_px)).astype(i32)
        redials = state.redials + committed.astype(i32)

        # consume the examined pool entry (success or fail) so a dead
        # candidate cannot wedge the controller
        pw = pool.shape[1]
        pool2 = jnp.where(
            use_px[:, None] & (jnp.arange(pw)[None, :] == k0[:, None]),
            -1, pool)
        out = (mesh, backoff, fmd, slow, warm, new_conns, new_rev, new_out,
               pool2, grafts, grafts_rx, px_grafts, redials)
        if dht_pool is not None:
            # same consume-on-examine rule for the DHT shortlist
            dw = dht_pool.shape[1]
            dpool2 = jnp.where(
                use_dht[:, None] & (jnp.arange(dw)[None, :] == dk0[:, None]),
                -1, dht_pool)
            out = out + (dpool2,)
        return out

    def _skip(_):
        out = (state.mesh_mask, state.backoff_until, state.fmd,
               state.slow_penalty, state.warm_offset_ms, conns, rev,
               out_mask, pool, state.grafts, state.grafts_rx,
               state.px_grafts, state.redials)
        if dht_pool is not None:
            out = out + (dht_pool,)
        return out

    fired = jax.lax.cond(want.any(), _fire, _skip, jnp.int32(0))
    (mesh, backoff, fmd, slow, warm, conns2, rev2, out2, pool2,
     grafts, grafts_rx, px_grafts, redials) = fired[:13]

    new_state = state.replace(
        mesh_mask=mesh, backoff_until=backoff, fmd=fmd, slow_penalty=slow,
        warm_offset_ms=warm, px_pool=pool2, starve_hb=starve, key=key,
        grafts=grafts, grafts_rx=grafts_rx,
        px_grafts=px_grafts, redials=redials,
    )
    if dht_pool is not None:
        return new_state, conns2, rev2, out2, fired[13]
    return new_state, conns2, rev2, out2


@partial(jax.jit,
         static_argnames=("params", "steps", "publisher", "batch_factor",
                          "telemetry"))
def run_recovery_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    steps: int,
    publisher: int = 0,
    batch_factor: int = 1,
    telemetry=None,
):
    """The post-attack recovery window: lax.scan of
    [heartbeat_step (evict/px branches armed) -> repair_round] x steps with
    the CONNECTION GRAPH in the carry — committed dials thread forward into
    every subsequent round's pulls, exactly like state.

    Unlike run_heartbeats/run_attacked_heartbeats, NOTHING hoists out of the
    scan: conns itself is loop-carried, so the per-step neighbor pull is
    load-bearing. Returns ((state, conns, rev, out_mask), obs) with obs
    leaves shaped (steps,) — the attack observables (shared with
    adversary_round, so campaign curves concatenate) plus per-round repair
    activity and the publisher's honest mesh degree (the eclipse-recovery
    signal).

    `telemetry`: optional armed ops/telemetry.TelemetryParams — the flight
    recorder's tel_* channels join the obs dict (disabled normalizes to
    None before the jit via the campaign caller; a disabled params passed
    here directly is treated as None so the trace stays identical)."""
    if telemetry is not None and not telemetry.enabled:
        telemetry = None

    def body(carry, _):
        s, cn, rv, om = carry
        ev0 = s.evictions.sum()
        px0 = s.px_grafts.sum()
        rd0 = s.redials.sum()
        s = heartbeat_step(s, cn, rv, om, params, batch_factor=batch_factor)
        s, cn, rv, om = repair_round(
            s, cn, rv, om, params, actor=~attacker,
            batch_factor=batch_factor)
        obs = attack_observables(s, cn, rv, attacker, params,
                                 batch_factor=batch_factor)
        f32 = jnp.float32
        nbr = cn[publisher]
        att_n = (nbr >= 0) & attacker[jnp.clip(nbr, 0)]
        obs["pub_honest_degree"] = (
            s.mesh_mask[publisher] & (nbr >= 0) & ~att_n).sum().astype(f32)
        obs["evictions"] = (s.evictions.sum() - ev0).astype(f32)
        obs["px_grafts"] = (s.px_grafts.sum() - px0).astype(f32)
        obs["redials"] = (s.redials.sum() - rd0).astype(f32)
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, cn, rv, params, telemetry, batch_factor=batch_factor))
        return (s, cn, rv, om), obs

    return jax.lax.scan(
        body, (state, conns, rev, out_mask), None, length=steps)


@partial(jax.jit,
         static_argnames=("params", "steps", "publisher", "batch_factor",
                          "telemetry"))
def _run_dht_recovery_heartbeats(state, conns, rev, out_mask, attacker,
                                 dht_pool, params, steps, publisher,
                                 batch_factor, telemetry):
    def body(carry, _):
        s, cn, rv, om, pool = carry
        ev0 = s.evictions.sum()
        px0 = s.px_grafts.sum()
        rd0 = s.redials.sum()
        s = heartbeat_step(s, cn, rv, om, params, batch_factor=batch_factor)
        s, cn, rv, om, pool = repair_round(
            s, cn, rv, om, params, actor=~attacker,
            batch_factor=batch_factor, dht_pool=pool, refuse=attacker)
        obs = attack_observables(s, cn, rv, attacker, params,
                                 batch_factor=batch_factor)
        f32 = jnp.float32
        nbr = cn[publisher]
        att_n = (nbr >= 0) & attacker[jnp.clip(nbr, 0)]
        obs["pub_honest_degree"] = (
            s.mesh_mask[publisher] & (nbr >= 0) & ~att_n).sum().astype(f32)
        obs["evictions"] = (s.evictions.sum() - ev0).astype(f32)
        obs["px_grafts"] = (s.px_grafts.sum() - px0).astype(f32)
        obs["redials"] = (s.redials.sum() - rd0).astype(f32)
        obs["dht_pool_left"] = (pool >= 0).sum().astype(f32)
        # the starvation-degradation signal: a peer whose every candidate
        # refuses keeps counting up — the curve must climb, never wedge
        obs["starve_max"] = s.starve_hb.max().astype(f32)
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, cn, rv, params, telemetry, batch_factor=batch_factor))
        return (s, cn, rv, om, pool), obs

    return jax.lax.scan(
        body, (state, conns, rev, out_mask, dht_pool), None, length=steps)


def run_dht_recovery_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    steps: int,
    dht_pool: jnp.ndarray | None = None,
    publisher: int = 0,
    batch_factor: int = 1,
    telemetry=None,
):
    """run_recovery_heartbeats with the discovery-backed candidate source:
    the (N, K) DHT shortlist rides the scan carry and feeds repair_round's
    re-dial path (refuse=attacker — sybil identities never accept), so a
    poisoned lookup measurably delays recovery and an exhausted pool
    degrades to monotone starvation instead of wedging. Returns
    ((state, conns, rev, out_mask, dht_pool), obs) with the extra
    `dht_pool_left` per-round channel.

    `dht_pool=None` LITERALLY delegates to run_recovery_heartbeats — same
    function object, same jit cache entry, bit-identical output shape and
    values, zero extra PRNG (tests/test_dht_adversary.py pins this)."""
    if dht_pool is None:
        return run_recovery_heartbeats(
            state, conns, rev, out_mask, attacker, params, steps,
            publisher=publisher, batch_factor=batch_factor,
            telemetry=telemetry)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    return _run_dht_recovery_heartbeats(
        state, conns, rev, out_mask, attacker, dht_pool, params, steps,
        publisher, batch_factor, telemetry)


@partial(jax.jit,
         static_argnames=("params", "adv", "steps", "publisher",
                          "batch_factor", "telemetry"))
def _run_adaptive_recovery_heartbeats(state, ctrl, conns, rev, out_mask,
                                      attacker, dht_pool, params, adv,
                                      steps, publisher, batch_factor,
                                      telemetry):
    pol = adv.adaptive
    # slot_race: the cohort runs the dial controller too, and its sybil
    # identities COMPLETE inbound handshakes (it wants the slot) — the
    # static model's refuse=attacker flips off
    actor = None if pol.slot_race else ~attacker
    refuse = None if pol.slot_race else (
        attacker if dht_pool is not None else None)
    # the PX poisoner's sybil-id schedule is scan-invariant even though the
    # graph is not: hoist it (nbr_ok must NOT hoist — conns is carried)
    n = conns.shape[0]
    att_sorted = jnp.sort(jnp.where(
        attacker, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)))
    n_att = attacker.sum()

    def body(carry, hb):
        if dht_pool is not None:
            s, c, cn, rv, om, pool = carry
        else:
            s, c, cn, rv, om = carry
            pool = None
        ev0 = s.evictions.sum()
        px0 = s.px_grafts.sum()
        rd0 = s.redials.sum()
        s = heartbeat_step(s, cn, rv, om, params, batch_factor=batch_factor)
        fired = repair_round(
            s, cn, rv, om, params, actor=actor, batch_factor=batch_factor,
            dht_pool=pool, refuse=refuse)
        if dht_pool is not None:
            s, cn, rv, om, pool = fired
        else:
            s, cn, rv, om = fired
        # the controller reacts AFTER the repair pass: re-grafts the slots
        # eviction just freed, re-poisons the pool repair just consumed
        (s, c), obs = adaptive_round(
            s, c, cn, rv, attacker, params, adv,
            batch_factor=batch_factor, hb_idx=hb,
            att_sorted=att_sorted, n_att=n_att)
        f32 = jnp.float32
        nbr = cn[publisher]
        att_n = (nbr >= 0) & attacker[jnp.clip(nbr, 0)]
        obs["pub_honest_degree"] = (
            s.mesh_mask[publisher] & (nbr >= 0) & ~att_n).sum().astype(f32)
        obs["evictions"] = (s.evictions.sum() - ev0).astype(f32)
        obs["px_grafts"] = (s.px_grafts.sum() - px0).astype(f32)
        obs["redials"] = (s.redials.sum() - rd0).astype(f32)
        if dht_pool is not None:
            obs["dht_pool_left"] = (pool >= 0).sum().astype(f32)
            obs["starve_max"] = s.starve_hb.max().astype(f32)
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, cn, rv, params, telemetry, batch_factor=batch_factor))
        carry = ((s, c, cn, rv, om, pool) if dht_pool is not None
                 else (s, c, cn, rv, om))
        return carry, obs

    carry0 = ((state, ctrl, conns, rev, out_mask, dht_pool)
              if dht_pool is not None
              else (state, ctrl, conns, rev, out_mask))
    return jax.lax.scan(body, carry0, jnp.arange(steps), length=steps)


def run_adaptive_recovery_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    steps: int,
    adv: AdversaryParams | None = None,
    ctrl: AdaptiveCtrl | None = None,
    dht_pool: jnp.ndarray | None = None,
    publisher: int = 0,
    batch_factor: int = 1,
    telemetry=None,
):
    """The ARMS-RACE recovery window: the repair controller heals the mesh
    while the adaptive adversary controller (ops/adversary.adaptive_round)
    contests every round of it — racing honest dialers for freed slots
    (actor=everyone, refuse=None: sybils dial AND accept), re-grafting
    edges the moment their backoff expires, re-poisoning the PX candidate
    pool right after repair consumes from it, and duty-cycling its own
    violation rate so the graylist never disarms it.

    Disabled (`adv` None or adv.adaptive.enabled False) this IS
    run_dht_recovery_heartbeats — the same call, the same jit cache entry,
    bit-identical, zero extra PRNG — which itself delegates to
    run_recovery_heartbeats when `dht_pool` is None; `ctrl` must be None
    then. Armed, the controller carry threads through the scan and the
    return widens to ((state, ctrl, conns, rev, out_mask[, dht_pool]),
    obs) with the adv_* channels joining the recovery obs."""
    if adv is None or not adv.adaptive.enabled:
        if ctrl is not None:
            raise ValueError("ctrl given but the adaptive policy is "
                             "disabled — the delegating path carries none")
        return run_dht_recovery_heartbeats(
            state, conns, rev, out_mask, attacker, params, steps,
            dht_pool=dht_pool, publisher=publisher,
            batch_factor=batch_factor, telemetry=telemetry)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if ctrl is None:
        ctrl = init_adaptive_ctrl(params.n)
    return _run_adaptive_recovery_heartbeats(
        state, ctrl, conns, rev, out_mask, attacker, dht_pool, params, adv,
        steps, publisher, batch_factor, telemetry)
