"""Protocol-generic step registry: the pub/sub arena's dispatch table.

The repo grew up simulating exactly one protocol — GossipSub v1.1 — and
its runners (ops/heartbeat.py, ops/adversary.py, ops/faults.py,
ops/disseminate.py) are the model of record, bit-pinned by the test canon
and conformance-gated against the numpy spec. A second protocol backend
(ops/episub.py) must face the SAME attacker on the SAME epoch graphs
without perturbing any of that, so the registry follows the house
delegation invariant taken to its logical end:

  the GossipSub ProtocolSpec's fields ARE the existing runner function
  objects — not wrappers, not re-exports through a shim, the very same
  Python callables. Dispatching `get_protocol("gossipsub").run_heartbeats`
  hits the same jit cache entry as calling ops.heartbeat.run_heartbeats
  directly, with zero retraces and bit-identical outputs, because it IS
  that call (tests/test_protocol_registry.py pins the `is` identity and
  the retrace count).

A ProtocolSpec mirrors the EntrypointContract pattern
(analysis/registry.py): a frozen declarative descriptor, with the
behavior living in the ops modules it points at. Per-protocol carry
(episub's tree controller) follows the AdaptiveCtrl discipline — a
separate pytree threaded only through the armed scans, never a SimState
leaf — so `init_ctrl=None` (GossipSub) means the runners keep their
pre-registry signatures exactly.

This module must stay free of the repo's jit idiom: it is a dispatch
table, not an entrypoint, and tests/test_registry_drift.py asserts the
GA-J/GA-S auditors need never see it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from .adversary import run_adaptive_heartbeats, run_attacked_heartbeats
from .disseminate import run_fused_rounds
from .faults import run_faulted_heartbeats
from .heartbeat import run_heartbeats


@dataclass(frozen=True)
class ProtocolSpec:
    """Frozen descriptor of one pub/sub protocol backend.

    Runner fields hold the module-level entrypoints with the house
    signatures (the run_heartbeats / run_attacked_heartbeats /
    run_adaptive_heartbeats / run_faulted_heartbeats argument contracts);
    protocols with extra carry (episub) prepend their ctrl pytree per the
    AdaptiveCtrl convention and set `init_ctrl`/`protocol_params`.

    `observables` names the per-round obs channels the attacked/adaptive
    runners emit BEYOND the shared attack_observables set — the campaign
    surfaces them per protocol in the arena artifact. `repair_hook` and
    `gossip_emission` name (for docs/auditors) how the backend realizes
    message repair and lazy gossip; the mechanics live in the runners.
    """

    name: str
    run_heartbeats: Callable
    run_attacked_heartbeats: Callable
    run_adaptive_heartbeats: Callable
    run_faulted_heartbeats: Callable
    # round-chained publish driver; None = protocol has no fused-mode
    # entrypoint (the campaign falls back to the phase-split chain)
    run_fused_rounds: Callable | None = None
    # fresh per-protocol controller carry for one trial window, or None
    # when the protocol carries everything in SimState (GossipSub)
    init_ctrl: Callable | None = None
    # fresh static per-protocol params (frozen dataclass -> jit static),
    # or None when SimParams alone configures the backend
    protocol_params: Callable | None = None
    repair_hook: str = ""
    gossip_emission: str = ""
    observables: tuple[str, ...] = field(default=())

    def validate(self) -> None:
        if not self.name:
            raise ValueError("ProtocolSpec needs a name")
        for f in ("run_heartbeats", "run_attacked_heartbeats",
                  "run_adaptive_heartbeats", "run_faulted_heartbeats"):
            if not callable(getattr(self, f)):
                raise ValueError(f"ProtocolSpec.{f} must be callable")


_PROTOCOLS: dict[str, ProtocolSpec] = {}


def register_protocol(spec: ProtocolSpec) -> ProtocolSpec:
    spec.validate()
    if spec.name in _PROTOCOLS:
        raise ValueError(f"protocol {spec.name!r} already registered")
    _PROTOCOLS[spec.name] = spec
    return spec


def get_protocol(name: str) -> ProtocolSpec:
    _ensure_builtin()
    try:
        return _PROTOCOLS[name]
    except KeyError:
        raise KeyError(
            f"unknown protocol {name!r}; registered: "
            f"{sorted(_PROTOCOLS)}") from None


def protocol_names() -> list[str]:
    _ensure_builtin()
    return sorted(_PROTOCOLS)


# -- builtin specs -----------------------------------------------------------
#
# GossipSub: the model of record. Every field is the existing module-level
# runner OBJECT — the registry adds a name, not a wrapper, so registry
# dispatch is the pre-registry call (same jit cache entry, zero retraces,
# bit-identical; the acceptance gate of the arena refactor).
#
# Episub is registered lazily to keep this module import-light and to
# avoid a circular import (episub reuses the adversary/fault machinery).

_BUILTIN_DONE = False


def _ensure_builtin() -> None:
    global _BUILTIN_DONE
    if _BUILTIN_DONE:
        return
    _BUILTIN_DONE = True
    register_protocol(ProtocolSpec(
        name="gossipsub",
        run_heartbeats=run_heartbeats,
        run_attacked_heartbeats=run_attacked_heartbeats,
        run_adaptive_heartbeats=run_adaptive_heartbeats,
        run_faulted_heartbeats=run_faulted_heartbeats,
        run_fused_rounds=run_fused_rounds,
        init_ctrl=None,
        protocol_params=None,
        repair_hook="IHAVE/IWANT gossip + mesh repair (ops/repair.py)",
        gossip_emission="gossip_factor sample of non-mesh peers, "
                        "d_lazy floor (ops/disseminate.py)",
        observables=(),
    ))

    from .episub import (EpisubParams, init_episub_ctrl,
                         run_episub_adaptive_heartbeats,
                         run_episub_attacked_heartbeats,
                         run_episub_faulted_heartbeats,
                         run_episub_heartbeats)

    register_protocol(ProtocolSpec(
        name="episub",
        run_heartbeats=run_episub_heartbeats,
        run_attacked_heartbeats=run_episub_attacked_heartbeats,
        run_adaptive_heartbeats=run_episub_adaptive_heartbeats,
        run_faulted_heartbeats=run_episub_faulted_heartbeats,
        run_fused_rounds=None,
        init_ctrl=init_episub_ctrl,
        protocol_params=EpisubParams,
        repair_hook="lazy IHAVE along non-tree edges + re-parenting "
                    "(ops/episub.py)",
        gossip_emission="d_lazy lowest-slot non-tree edges per round",
        observables=("tree_reach_frac", "tree_depth_mean"),
    ))
