"""Episub: a Topiary-style eager-push tree backend (arXiv:2312.06800).

The second protocol in the arena (ops/protocol.py). Where GossipSub
maintains a redundant D-regular mesh, episub maintains a spanning TREE
rooted at the publisher: each peer adopts its minimum-hop valid neighbor
as parent (distributed Bellman-Ford relaxation, one neighbor-pull per
heartbeat), eager-pushes only along parent/child edges, and advertises
lazily (IHAVE-style) along up to d_lazy non-tree edges so a broken
branch can be repaired through the message-grain gossip machinery. The
trade the arena measures is exactly Topiary's: ~N-1 eager edges instead
of ~N*D/2, so far lower amplification, bought with a single point of
structural failure per subtree.

Everything reuses the house machinery:

  * SimState is shared unchanged — the tree IS mesh_mask (the eager-push
    edge set disseminate forwards along), so publish/delivery, telemetry
    channels, faults, and the adversary all compose without a new code
    path. Non-mesh edges are episub's lazy channel, which is precisely
    what disseminate's gossip emission already samples.
  * Per-protocol carry (hop estimates, parent slots) follows the
    AdaptiveCtrl discipline (ops/state.py): a separate EpisubCtrl pytree
    threaded through the armed scans, never a SimState leaf, so the
    GossipSub traces cannot grow a dead carry by construction.
  * Scoring compatibility: an edge whose score sank below
    params.graylist_threshold is neither an acceptable parent nor an
    accepted child — the attacker faces the same graylist defense on
    both backends (static-gated like the engine: with non-negative
    weights the comparison compiles out).
  * Re-parenting on churn/eviction is implicit: a dead/partitioned/
    graylisted parent falls out of the validity mask, its children's
    candidate hops go to INF, and the next relaxation adopts the best
    surviving neighbor. A detached subtree's stale hop estimates can
    only count UP (classic Bellman-Ford), so candidates are clamped at
    N hops — a component with no finite-hop path to the root drains to
    unreached within N rounds instead of counting to infinity.

Determinism: ties in the parent choice resolve to the LOWEST NEIGHBOR
SLOT (jnp.argmin's first-occurrence rule) — the same deterministic
slot-order policy the spec's opportunistic-grafting tie break documents
(ops/spec.py opportunistic_graft_candidates). The step consumes PRNG
only for churn (3 splits, unconditionally, mirroring heartbeat_step's
fixed key schedule so a fixed seed gives a reproducible trajectory).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .adversary import AdversaryParams, adaptive_round, adversary_round
from .faults import FaultParams, partition_edge_mask
from .heartbeat import _apply_decay
from .pull import neighbor_pull_bool, neighbor_pull_min, reciprocal_pull_bool
from .state import (SimParams, SimState, init_adaptive_ctrl, repair_inert,
                    restore_repair, strip_repair)

# numpy, NOT jnp: the protocol registry imports this module lazily, and
# the first import can happen INSIDE an active jit trace (a campaign
# window resolving get_protocol under lowering) — a module-level
# jnp.float32 would bind a tracer from that trace to the global and leak
# it into every later compile as a phantom hoisted parameter
INF = np.float32(3.4e38)


@dataclass(frozen=True)
class EpisubParams:
    """Static episub configuration (hashable -> jit static arg).

    `root`: the tree root's peer id — the arena pins it to the trial's
    publisher so the eager tree points the way the traffic flows.
    `lazy_degree`: per-round IHAVE advertisement budget along non-tree
    edges; None defers to params.d_lazy (the GossipSub lazy floor, the
    fair default for head-to-head runs)."""

    root: int = 0
    lazy_degree: int | None = None

    def validate(self, n: int) -> None:
        if not (0 <= self.root < n):
            raise ValueError(f"root must be in [0, {n}), got {self.root}")
        if self.lazy_degree is not None and self.lazy_degree < 0:
            raise ValueError("lazy_degree must be >= 0")


@struct.dataclass
class EpisubCtrl:
    """On-device per-peer tree state, (N,). `hops` is the peer's current
    estimate of its hop distance to the root (INF = unreached); `parent`
    is the NEIGHBOR SLOT of its parent edge (-1 = none — the root, or a
    detached peer); `reparents` counts parent changes (the episub analog
    of the graft/prune control churn)."""

    hops: jnp.ndarray       # (N,) f32 hop estimate to root; INF unreached
    parent: jnp.ndarray     # (N,) i32 parent neighbor slot; -1 = none
    reparents: jnp.ndarray  # (N,) i32 cumulative parent changes


def init_episub_ctrl(n: int) -> EpisubCtrl:
    """Fresh (fully detached) tree carry for one trial window."""
    return EpisubCtrl(
        hops=jnp.full((n,), 3.4e38, dtype=jnp.float32),
        parent=jnp.full((n,), -1, dtype=jnp.int32),
        reparents=jnp.zeros((n,), dtype=jnp.int32),
    )


def episub_observables(ctrl: EpisubCtrl, alive: jnp.ndarray,
                       subscribed: jnp.ndarray) -> dict:
    """The per-round episub obs channels (ProtocolSpec.observables):
    tree_reach_frac — fraction of live subscribed peers with a finite
    hop estimate (the tree's coverage of the peer set); tree_depth_mean
    — mean hop distance over reached peers (the eager path length)."""
    n = ctrl.hops.shape[0]
    live = alive & subscribed
    reached = live & (ctrl.hops <= jnp.float32(n))
    n_r = jnp.maximum(reached.sum(), 1)
    return {
        "tree_reach_frac": (reached.sum()
                            / jnp.float32(jnp.maximum(live.sum(), 1))),
        "tree_depth_mean": (jnp.where(reached, ctrl.hops, 0.0).sum()
                            / jnp.float32(n_r)),
    }


@partial(jax.jit, static_argnames=("params", "ep", "batch_factor"))
def episub_heartbeat_step(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    batch_factor: int = 1,
    nbr_ok: jnp.ndarray | None = None,
    edge_ok: jnp.ndarray | None = None,
):
    """One episub heartbeat: hop relaxation -> parent adoption -> tree
    edge set -> lazy IHAVE budget -> score decay. Same optional-arg
    contract as heartbeat_step: `nbr_ok` hoists the liveness pull out of
    churn-free scans, `edge_ok` is the fault-injection hook. Returns
    (state, ctrl); mesh_mask on return IS the tree (parent edge plus
    accepted child edges), which disseminate eager-pushes along."""
    n, c = conns.shape
    key, k_churn_d, k_churn_u = jax.random.split(state.key, 3)
    t = state.t_ms

    # -- churn (same schedule semantics as heartbeat_step) -------------------
    alive = state.alive
    if params.churn_down_per_hb > 0.0 or params.churn_up_per_hb > 0.0:
        dies = jax.random.uniform(k_churn_d, (n,)) < params.churn_down_per_hb
        revives = jax.random.uniform(k_churn_u, (n,)) < params.churn_up_per_hb
        alive = jnp.where(alive, ~dies, revives)
        nbr_ok = None   # alive just changed; precomputed masks are stale
        warm = jnp.full_like(state.warm_offset_ms, 3.4e38)
    else:
        warm = state.warm_offset_ms

    if nbr_ok is None:
        nbr_ok = neighbor_pull_bool(
            alive & state.subscribed, conns, rev, batch_factor)
    valid = ((conns >= 0) & alive[:, None] & nbr_ok
             & state.subscribed[:, None])
    if edge_ok is not None:
        valid = valid & edge_ok

    # scoring-compatible graylist: a graylisted edge is neither a parent
    # candidate nor an accepted child. Static-gated exactly like the
    # engine's threshold machinery — with non-negative score weights the
    # floor can never bind and the compare compiles out.
    _gray = params.slow_weight < 0.0 or params.fmd_weight < 0.0
    if _gray:
        ok_edge = valid & (state.score(params) >= params.graylist_threshold)
    else:
        ok_edge = valid

    # -- hop relaxation + parent adoption ------------------------------------
    # pull every neighbor's hop estimate (INF on invalid slots), relax by
    # one hop, clamp runaway estimates at N (a detached subtree's stale
    # values count up, never down — the clamp drains it to unreached in
    # at most N rounds instead of forever)
    is_root = jnp.arange(n) == ep.root
    nbr_hops = neighbor_pull_min(ctrl.hops, conns, rev, batch_factor)
    cand = jnp.where(ok_edge & (nbr_hops < jnp.float32(n)),
                     nbr_hops + 1.0, INF)
    best = cand.min(axis=-1)
    best_slot = jnp.argmin(cand, axis=-1).astype(jnp.int32)  # lowest slot
    # parent damping: keep the incumbent while it still achieves the
    # minimum — re-parenting only on strict improvement or parent loss
    # keeps the tree stable under score noise
    old = ctrl.parent
    old_cand = jnp.take_along_axis(
        cand, jnp.clip(old, 0)[:, None], axis=-1)[:, 0]
    keep_old = (old >= 0) & (old_cand <= best)
    slot = jnp.where(keep_old, jnp.clip(old, 0), best_slot)
    reachable = best <= jnp.float32(n)
    has_parent = reachable & ~is_root & alive & state.subscribed
    root_live = is_root & alive & state.subscribed
    hops = jnp.where(root_live, 0.0,
                     jnp.where(has_parent,
                               jnp.take_along_axis(
                                   cand, slot[:, None], axis=-1)[:, 0],
                               INF))
    parent = jnp.where(has_parent, slot, jnp.int32(-1))

    # -- tree edge set: my parent edge + accepted child edges ----------------
    parent_edge = ((jnp.arange(c, dtype=jnp.int32)[None, :]
                    == parent[:, None]) & has_parent[:, None])
    child_edge = reciprocal_pull_bool(parent_edge, conns, rev, batch_factor)
    if _gray:
        child_edge = child_edge & ok_edge  # refuse graylisted children
    tree = (parent_edge | child_edge) & valid

    # re-parent accounting: a parent change is a GRAFT to the new parent
    # and (when an old parent existed) a PRUNE of the old edge — counted
    # in the shared control ledgers so the telemetry channels compare
    # across protocols
    moved = parent != old
    i32 = jnp.int32
    reparents = ctrl.reparents + (moved & (old >= 0)).astype(i32)
    grafts = state.grafts + (moved & has_parent).astype(i32)
    prunes = state.prunes + (moved & (old >= 0)).astype(i32)

    # -- lazy IHAVE channel: advertise along up to lazy_degree non-tree
    # edges per round (lowest slots first — deterministic, PRNG-free).
    # This is the heartbeat-grain tree-repair advertisement; message-grain
    # repair rides disseminate's gossip over the same non-mesh edges.
    lazy_budget = params.d_lazy if ep.lazy_degree is None else ep.lazy_degree
    lazy = valid & ~tree
    sel = lazy & (jnp.cumsum(lazy, axis=-1) <= lazy_budget)
    ihave_tx = state.ihave_tx + sel.sum(axis=-1, dtype=i32)
    ihave_rx = state.ihave_rx + reciprocal_pull_bool(
        sel, conns, rev, batch_factor).sum(axis=-1, dtype=i32)

    # -- score decay (identical gated formula to heartbeat_step) -------------
    def do_decay(fmd, slow):
        return (_apply_decay(fmd, params.fmd_decay, params),
                _apply_decay(slow, params.slow_decay, params))

    fmd, slow = jax.lax.cond(
        ((state.fmd > 0) | (state.slow_penalty > 0)).any(),
        do_decay,
        lambda f, s: (f, s),
        state.fmd, state.slow_penalty,
    )

    new_state = state.replace(
        mesh_mask=tree,
        fmd=fmd,
        slow_penalty=slow,
        alive=alive,
        warm_offset_ms=warm,
        t_ms=t + params.heartbeat_ms,
        key=key,
        grafts=grafts,
        prunes=prunes,
        ihave_tx=ihave_tx,
        ihave_rx=ihave_rx,
    )
    new_ctrl = EpisubCtrl(hops=hops, parent=parent, reparents=reparents)
    return new_state, new_ctrl


def run_episub_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    steps: int,
    batch_factor: int = 1,
):
    """lax.scan of episub_heartbeat_step x steps -> (state, ctrl). The
    runner contract mirrors run_heartbeats (strip_repair around the jit
    when repair is inert, static steps for segment cache hits) with the
    ctrl carry prepended per the ProtocolSpec convention."""
    ep.validate(params.n)
    if repair_inert(params):
        state, saved = strip_repair(state)
        out, ctrl = _run_episub_heartbeats(
            state, ctrl, conns, rev, out_mask, params, ep, steps,
            batch_factor)
        return restore_repair(out, saved), ctrl
    return _run_episub_heartbeats(
        state, ctrl, conns, rev, out_mask, params, ep, steps, batch_factor)


@partial(jax.jit, static_argnames=("params", "ep", "steps", "batch_factor"))
def _run_episub_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    steps: int,
    batch_factor: int = 1,
):
    nbr_ok = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    def body(carry, _):
        s, c = carry
        s, c = episub_heartbeat_step(
            s, c, conns, rev, out_mask, params, ep,
            batch_factor=batch_factor, nbr_ok=nbr_ok)
        return (s, c), None

    (state, ctrl), _ = jax.lax.scan(body, (state, ctrl), None, length=steps)
    return state, ctrl


def run_episub_attacked_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    """lax.scan of [episub_heartbeat_step -> adversary_round] x steps ->
    ((state, ctrl), obs). The SAME adversary_round as GossipSub's window
    — the arena's whole point: the attacker's graft flood lands in
    mesh_mask after the tree write, so attack edges carry eager traffic
    until the next relaxation recomputes the tree (and the graylist
    blocks a penalized attacker from ever becoming a parent). Obs adds
    the episub channels (tree_reach_frac, tree_depth_mean) to the shared
    attack_observables set."""
    ep.validate(params.n)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if repair_inert(params):
        state, saved = strip_repair(state)
        (out, ctrl), obs = _run_episub_attacked_heartbeats(
            state, ctrl, conns, rev, out_mask, attacker, params, ep, adv,
            steps, batch_factor, telemetry)
        return (restore_repair(out, saved), ctrl), obs
    return _run_episub_attacked_heartbeats(
        state, ctrl, conns, rev, out_mask, attacker, params, ep, adv, steps,
        batch_factor, telemetry)


@partial(jax.jit, static_argnames=("params", "ep", "adv", "steps",
                                   "batch_factor", "telemetry"))
def _run_episub_attacked_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    nbr_ok = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    xs = jnp.arange(steps) if adv.identity_rotation else None

    def body(carry, hb):
        s, c = carry
        s, c = episub_heartbeat_step(
            s, c, conns, rev, out_mask, params, ep,
            batch_factor=batch_factor, nbr_ok=nbr_ok)
        s, obs = adversary_round(s, conns, rev, attacker, params, adv,
                                 batch_factor=batch_factor, nbr_ok=nbr_ok,
                                 hb_idx=hb)
        obs.update(episub_observables(c, s.alive, s.subscribed))
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        return (s, c), obs

    return jax.lax.scan(body, (state, ctrl), xs, length=steps)


def run_episub_adaptive_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    steps: int,
    actrl=None,
    batch_factor: int = 1,
    telemetry=None,
):
    """The adaptive attack window against the tree. Disabled
    (`not adv.adaptive.enabled`) this IS run_episub_attacked_heartbeats
    — the same call, the same jit cache entry, the house delegation
    invariant — and `actrl` must be None. Armed, the adaptive controller
    carry threads alongside the tree carry and the return widens to
    ((state, ctrl, actrl), obs)."""
    if not adv.adaptive.enabled:
        if actrl is not None:
            raise ValueError("actrl given but adv.adaptive is disabled — "
                             "the disabled path delegates to "
                             "run_episub_attacked_heartbeats and carries "
                             "none")
        return run_episub_attacked_heartbeats(
            state, ctrl, conns, rev, out_mask, attacker, params, ep, adv,
            steps, batch_factor, telemetry)
    ep.validate(params.n)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if actrl is None:
        actrl = init_adaptive_ctrl(params.n)
    if repair_inert(params):
        state, saved = strip_repair(state)
        (out, ctrl, actrl), obs = _run_episub_adaptive_heartbeats(
            state, ctrl, actrl, conns, rev, out_mask, attacker, params, ep,
            adv, steps, batch_factor, telemetry)
        return (restore_repair(out, saved), ctrl, actrl), obs
    return _run_episub_adaptive_heartbeats(
        state, ctrl, actrl, conns, rev, out_mask, attacker, params, ep, adv,
        steps, batch_factor, telemetry)


@partial(jax.jit, static_argnames=("params", "ep", "adv", "steps",
                                   "batch_factor", "telemetry"))
def _run_episub_adaptive_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    actrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    nbr_ok = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    # the PX poisoner's sybil-id schedule is scan-invariant: hoist it
    n = conns.shape[0]
    att_sorted = jnp.sort(jnp.where(
        attacker, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)))
    n_att = attacker.sum()

    def body(carry, hb):
        s, c, a = carry
        s, c = episub_heartbeat_step(
            s, c, conns, rev, out_mask, params, ep,
            batch_factor=batch_factor, nbr_ok=nbr_ok)
        (s, a), obs = adaptive_round(
            s, a, conns, rev, attacker, params, adv,
            batch_factor=batch_factor, nbr_ok=nbr_ok, hb_idx=hb,
            att_sorted=att_sorted, n_att=n_att)
        obs.update(episub_observables(c, s.alive, s.subscribed))
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        return (s, c, a), obs

    return jax.lax.scan(body, (state, ctrl, actrl), jnp.arange(steps),
                        length=steps)


def run_episub_faulted_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    faults: FaultParams,
    crash: jnp.ndarray,
    side: jnp.ndarray,
    spike: jnp.ndarray,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
    actrl=None,
):
    """The fault-armed episub window (crash / partition / spike cohorts,
    ops/faults.py window semantics). Disabled this IS the adaptive (or
    attacked) episub runner — the same delegation chain as
    run_faulted_heartbeats. Armed, the fault schedule differs from the
    GossipSub window in ONE deliberate way: there is no freeze/thaw mesh
    bank, because the tree re-derives from the hop relaxation every
    round — a partition simply re-parents both sides (the cut side with
    no root drains to unreached), and healing re-merges the tree without
    banked state. A crashed peer goes dark by cohort edge-mask (its hop
    estimate drains to INF, its children re-parent) and returns cold
    (parent=-1 semantics emerge from the relaxation, no state surgery
    needed)."""
    ep.validate(params.n)
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if not faults.enabled:
        if adv.adaptive.enabled:
            return run_episub_adaptive_heartbeats(
                state, ctrl, conns, rev, out_mask, attacker, params, ep,
                adv, steps, actrl=actrl, batch_factor=batch_factor,
                telemetry=telemetry)
        if actrl is not None:
            raise ValueError("actrl given but the adaptive policy is "
                             "disabled — the delegating path carries none")
        return run_episub_attacked_heartbeats(
            state, ctrl, conns, rev, out_mask, attacker, params, ep, adv,
            steps, batch_factor, telemetry)
    if adv.adaptive.enabled and actrl is None:
        actrl = init_adaptive_ctrl(params.n)
    if not adv.adaptive.enabled and actrl is not None:
        raise ValueError("actrl given but the adaptive policy is disabled")
    if repair_inert(params):
        state, saved = strip_repair(state)
        out, obs = _run_episub_faulted_heartbeats(
            state, ctrl, actrl, conns, rev, out_mask, attacker, crash, side,
            spike, params, ep, adv, faults, steps, batch_factor, telemetry)
        if adv.adaptive.enabled:
            out2, ctrl, actrl = out
            return (restore_repair(out2, saved), ctrl, actrl), obs
        out2, ctrl = out
        return (restore_repair(out2, saved), ctrl), obs
    return _run_episub_faulted_heartbeats(
        state, ctrl, actrl, conns, rev, out_mask, attacker, crash, side,
        spike, params, ep, adv, faults, steps, batch_factor, telemetry)


@partial(jax.jit, static_argnames=("params", "ep", "adv", "faults", "steps",
                                   "batch_factor", "telemetry"))
def _run_episub_faulted_heartbeats(
    state: SimState,
    ctrl: EpisubCtrl,
    actrl,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    attacker: jnp.ndarray,
    crash: jnp.ndarray,
    side: jnp.ndarray,
    spike: jnp.ndarray,
    params: SimParams,
    ep: EpisubParams,
    adv: AdversaryParams,
    faults: FaultParams,
    steps: int,
    batch_factor: int = 1,
    telemetry=None,
):
    adaptive = adv.adaptive.enabled
    if adaptive:
        n_rows = conns.shape[0]
        att_sorted = jnp.sort(jnp.where(
            attacker, jnp.arange(n_rows, dtype=jnp.int32), jnp.int32(n_rows)))
        n_att = attacker.sum()
    nbr_ok = None
    if (params.churn_down_per_hb == 0.0
            and params.churn_up_per_hb == 0.0):
        # crash goes through edge_ok here (no alive surgery), so liveness
        # stays scan-invariant without churn and the pull hoists
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)

    cross = partition_edge_mask(side, conns) if faults.partition else None
    if faults.crash:
        crash_nbr = neighbor_pull_bool(crash, conns, rev, batch_factor)
        crash_edges = ((crash[:, None] | crash_nbr) & (conns >= 0))

    def body(carry, hb):
        if adaptive:
            s, c, a = carry
        else:
            s, c = carry
        edge_ok = None
        if faults.crash:
            cs, ce = faults.crash_window
            dark = (hb >= cs) & (hb < ce)
            edge_ok = jnp.where(dark, ~crash_edges, True)
        if faults.partition:
            ps, pe = faults.partition_window
            cut = jnp.where((hb >= ps) & (hb < pe), ~cross, True)
            edge_ok = cut if edge_ok is None else (edge_ok & cut)
        s, c = episub_heartbeat_step(
            s, c, conns, rev, out_mask, params, ep,
            batch_factor=batch_factor, nbr_ok=nbr_ok, edge_ok=edge_ok)
        if adaptive:
            (s, a), obs = adaptive_round(
                s, a, conns, rev, attacker, params, adv,
                batch_factor=batch_factor, nbr_ok=nbr_ok, edge_ok=edge_ok,
                hb_idx=hb, att_sorted=att_sorted, n_att=n_att)
        else:
            s, obs = adversary_round(
                s, conns, rev, attacker, params, adv,
                batch_factor=batch_factor, nbr_ok=nbr_ok, edge_ok=edge_ok,
                hb_idx=hb)
        if faults.spike:
            ss, se = faults.spike_window
            live = (hb >= ss) & (hb < se)
            s = s.replace(uplink_free_ms=jnp.where(
                spike & live,
                jnp.maximum(s.uplink_free_ms, s.t_ms)
                + jnp.float32(faults.spike_ms),
                s.uplink_free_ms))
        obs.update(episub_observables(c, s.alive, s.subscribed))
        f32 = jnp.float32
        if faults.partition:
            obs["cross_mesh_edges"] = (s.mesh_mask & cross).sum().astype(f32)
        if faults.crash:
            obs["restarted_mean_degree"] = (
                (s.mesh_mask & crash[:, None]).sum()
                / f32(jnp.maximum(crash.sum(), 1)))
        if telemetry is not None:
            from .telemetry import telemetry_observables

            obs.update(telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor))
        if adaptive:
            return (s, c, a), obs
        return (s, c), obs

    xs = jnp.arange(steps)
    if adaptive:
        (state, ctrl, actrl), obs = jax.lax.scan(
            body, (state, ctrl, actrl), xs, length=steps)
        return (state, ctrl, actrl), obs
    (state, ctrl), obs = jax.lax.scan(body, (state, ctrl), xs, length=steps)
    return (state, ctrl), obs
