"""Message dissemination as an earliest-arrival-time fixpoint (the hot path).

The reference measures one thing above all: per-message dissemination latency
— a publisher embeds a nanosecond timestamp, every receiver logs
`<msgId> milliseconds: <delay>` (gossipsub-queues/main.nim:126-154), and awk
aggregates (shadow/summary_latency*.awk). Shadow produces those delays with a
full per-packet discrete-event simulation; we produce them as the fixpoint of

    t_rx[q] = max( min over senders p of
                     max(t_rx[p] + proc, uplink_free[p])
                     + (rank_p(q)+1) * tx_p + LAT[stage_p, stage_q],
                   rx_free[q] + rx_ms[q] )

where rank_p(q) is q's position in p's randomized send order (uplink
serialization: a peer forwarding B bytes to k mesh members occupies its own
uplink k times in sequence — Shadow's dominant queueing effect for 15 KB
messages, acknowledged by summary_latency_large.awk:20-24), LAT is the
stage-pair latency matrix from the topology, and uplink_free carries the
drain time of EARLIER messages (SimState): concurrent publishes queue
behind each other the way the reference's per-connection queues serialize
all in-flight traffic.

The data-carrying link traversal additionally pays TCP slow-start flight
dynamics (tcp_flights below): under Shadow the nodes run REAL TCP stacks
(regression/Dockerfile_amd64_shadow:3-11), so a transfer larger than the
~14.6 KB initial congestion window needs multiple RTT-gated flights and the
per-edge delivery latency becomes lat * (1 + 2*(flights-1)) — the flagship
15 KB message pays +1 RTT per hop, a 128 KB block +3. Publishes are seconds
apart, so windows slow-start-restart after idling (RFC 2861) and cold is
the default state; mesh fragments of one message ride a warmed back-to-back
stream, gossip answers restart cold. Control packets (IHAVE/IWANT/
IDONTWANT) fit the first window and keep the bare latency.

The outer max is the RECEIVER side of the same bandwidth story: Shadow
enforces host_bandwidth_down on every host (shadow/topogen.py:50-51), so a
copy of rx_ms[q] = bytes/bw_down drain time arriving while q's downlink is
still busy with earlier traffic completes only when that backlog clears
plus its own drain — the single-server queue completion
max(wire_arrival, busy_until + rx_ms). When the downlink is idle the copy
streams through concurrently with the sender's serialization (bw_down ==
bw_up per stage in the reference topology) and completes at its wire
arrival: no double-counted serialization. rx_free is carried in SimState
(write-back below folds ALL delivered copies — duplicates and gossip
answers included — through the queue in arrival order, exactly).
Cross-fragment rx contention inside one message is not modeled: same-sender
fragments are spaced k*tx >= rx_ms apart by the uplink queue, so only
interleaved different-sender duplicates could bind, a second-order effect.
Answered IWANTs SERIALIZE on the answering uplink (gossip_fold below): a
peer answering k IWANTs in one gossip round transmits the answers
back-to-back in IWANT-arrival order — sum, not max — and a round's backlog
spills into the next round's queue, the way the reference's per-connection
queues all drain through one host_bandwidth_up (main.nim:264-299). The DES
cross-check reproduces this through a chronological event heap (IHAVE
arrival -> IWANT -> single-server answer queue), written independently of
the fixpoint's sorted-prefix fold, so the differential suite discriminates
exactly this term. (Cross-fragment answer serialization within one message
remains uncoupled — fragment lanes are vmapped — matching the per-fragment
independence of everything else inside a message.)
The whole model is differentially validated against that independent
host-side event-queue simulator (tests/test_des_crosscheck.py).

The iteration is a *pull*: each peer gathers its neighbors' sender-side
candidate times through the reverse-slot map (ops/graph.py) — two gathers and
a row-min, no scatter, no dynamic shapes. Because arrival times decrease
monotonically, the fixpoint equals the discrete-event result for this link
model. The fixpoint runs twice per fragment: once to discover each peer's
first sender, then again with the back-edge removed from the send order (the
reference never forwards a message back to the peer that delivered it, so
that uplink slot is never occupied).

IHAVE/IWANT gossip joins the same fixpoint as extra candidate edges
quantized to the emitter's heartbeat ticks (IHAVE -> IWANT -> message =
3 link traversals + one serialization). Targets re-sample EVERY heartbeat
over the mcache history window (history_gossip rounds, main.nim:259,283);
since each round's offer grows by one heartbeat, the window collapses to a
per-edge first-sampled-round offset inside the fixpoint. Heartbeat phases
are persistent per-node state. Post-fixpoint, a single accounting pass
yields duplicate deliveries, per-peer tx/rx bytes, per-peer bidirectional
IHAVE/IWANT/IDONTWANT counts, IDONTWANT suppression
(go-test-node/main.go:165), v1.1 score-threshold gating, and
firstMessageDeliveries score credit.

Fragmentation (FRAGMENTS > 1, main.nim:177-179) vmaps everything over the
fragment axis; a relay's uplink additionally carries the f earlier fragments
(f * k_p extra serialization slots) and a message completes at a receiver
when its LAST fragment lands (main.nim:147-148).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from ..parallel.exchange import (
    build_recv_constants,
    converge_recv,
    converge_sharded,
)
from .pull import (
    exceeds_budget,
    neighbor_pull_bool,
    neighbor_pull_min,
    reciprocal_pull_bool,
    reciprocal_pull_min,
)
from .state import SimParams, SimState

INF = jnp.float32(3.4e38)
# any warm_offset_ms at or above this is "no valid carry" (init / churned /
# never-arrived peers store INF); real arrival offsets are orders of
# magnitude smaller
WARM_VALID = jnp.float32(1e30)

# TCP retransmission model (loss_mode="tcp"). Under Shadow, nodes run real
# TCP stacks over the lossy GML edges (regression/Dockerfile_amd64_shadow:
# 3-11 — LD_PRELOAD interposition of real sockets), so per-packet loss
# mostly becomes ADDED LATENCY, not lost coverage: the segment is
# retransmitted after an RTO, doubling per RFC 6298 on repeat failures.
#   RTO_edge      = max(RTO_MIN_MS, 1.5 * RTT)   (SRTT + 4*RTTVAR with
#                   RTTVAR ~ RTT/8 at steady state; Linux clamps at
#                   tcp_rto_min = 200 ms)
#   retx delay(j) = sum_{k<j} RTO * 2^k = RTO * (2^j - 1)   after j failures
#   j ~ Geometric(p): P(j >= k) = p^k, sampled once per FRAGMENT per
#                   directed edge (each fragment is a distinct GossipSub
#                   message upstream; per-packet re-draws are below the
#                   model's granularity)
#   j > MAX_RETRIES -> the copy is abandoned (prob p^(MAX_RETRIES+1);
#                   at topogen-scale loss rates this is negligible, so
#                   coverage stays ~1.0 and the loss knob moves p99 —
#                   the Shadow-faithful behavior). Retransmitted bytes are
#                   not re-billed to the uplink queue (second-order next
#                   to the >= 200 ms RTO stall; documented approximation).
RTO_MIN_MS = 200.0
MAX_RETRIES = 6


def tcp_flights(nbytes: int, params) -> int:
    """Number of RTT-gated TCP flights a cold-started transfer of `nbytes`
    needs. Under Shadow the nodes run real TCP stacks
    (regression/Dockerfile_amd64_shadow:3-11): the first flight carries at
    most initcwnd_segments * mss_bytes (Linux IW10, RFC 6928) and the
    congestion window doubles each RTT while slow-starting, so after F
    flights IW * (2^F - 1) bytes are out. Messages are published seconds
    apart (topogen delay_seconds), so connections slow-start-restart after
    idling (RFC 2861) and EVERY data transfer starts cold — this is the
    default state, not a corner case. The large-message statistic the
    reference acknowledges as TxTime-confounded (summary_latency_large.awk:
    20-24) is exactly this multi-flight effect.

    Closed form: smallest F >= 1 with IW * (2^F - 1) >= nbytes.
    (The DES cross-check derives the same count with an independent loop
    formulation — tests/test_des_crosscheck.py.)"""
    import math

    if not params.slow_start:
        return 1
    iw = params.mss_bytes * params.initcwnd_segments
    if nbytes <= iw:
        return 1
    f = max(1, math.ceil(math.log2(nbytes / iw + 1.0)))
    # integer-exact boundary correction (the float log can land a hair off
    # when nbytes sits exactly on a window-sum boundary)
    while f > 1 and iw * (2 ** (f - 1) - 1) >= nbytes:
        f -= 1
    while iw * (2 ** f - 1) < nbytes:
        f += 1
    return f


@struct.dataclass
class DisseminationResult:
    t_rx_ms: jnp.ndarray       # (N,) absolute full-receipt time, INF if never
    delay_ms: jnp.ndarray      # (N,) t_rx - t0, INF if never
    received: jnp.ndarray      # (N,) bool (all fragments)
    sends: jnp.ndarray         # (N,) int32 message copies sent by each peer
    copies_rx: jnp.ndarray     # (N,) int32 copies received (>=1 => received)
    ihave_sent: jnp.ndarray    # (N,) int32 IHAVEs sent per peer
    iwant_sent: jnp.ndarray    # (N,) int32 IWANTs sent per peer
    lost_tx: jnp.ndarray       # (N,) int32 transmitted copies the network
    #                            never delivered: loss_mode="tcp" abandons a
    #                            copy after MAX_RETRIES RTOs (prob
    #                            p^(MAX_RETRIES+1) per fragment-edge), the
    #                            "message" mode loses it outright. Lossy runs
    #                            verify the tcp-mode negligibility claim
    #                            against this counter instead of trusting it.
    answer_wait_max_ms: jnp.ndarray  # () float32 — bounded delivery mode
    #                            (params.serialize_answers=False) ONLY: the
    #                            max time any requested gossip answer waited
    #                            queued behind another at the final times —
    #                            the per-hop bound on how far an arrival
    #                            time may sit below the exact serialized
    #                            model's. 0.0 in the exact default mode
    #                            (the repair makes the times exact) and
    #                            whenever no answer ever queued. ALWAYS
    #                            finite: when announce rounds interleave the
    #                            per-round fold's bound does not cover the
    #                            interleaved corner — that condition is
    #                            reported separately in answer_interleaved
    #                            instead of the former INF poison (which
    #                            leaked invalid-JSON `Infinity` into bench
    #                            artifacts).
    answer_interleaved: jnp.ndarray  # () int32 — bounded mode: number of
    #                            fragment lanes whose gossip-answer rounds
    #                            INTERLEAVED at the final times (a round's
    #                            earliest requested IWANT arriving before
    #                            the previous round's latest), where the
    #                            fold's wait bar under-reports. 0 in exact
    #                            mode (interleaving routes to the global-
    #                            sort slow path and is repaired).
    converged: jnp.ndarray     # () bool — every fixpoint this result rode
    #                            (the per-fragment phase relaxations; in
    #                            exact mode also the serialized outer
    #                            iteration) reached self-consistency before
    #                            its iteration cap. False means some loop
    #                            was CUT at params.max_relax_iters and the
    #                            times/error bar may be off — previously
    #                            this was silently reported as exact.
    refine_passes: jnp.ndarray  # () int32 — exact mode only: refinement
    #                            iterations the serialized-answer repair
    #                            spent, max over fragment lanes (prefix
    #                            mode: Jacobi iterations of both phases;
    #                            after a fallback to the global-sort path,
    #                            the prefix iterations already spent plus
    #                            the serial outer passes). 0 whenever the
    #                            fast pipeline was kept (no queued answer
    #                            could have been a first delivery) and in
    #                            bounded / no-gossip mode. The tier-1
    #                            pass-count budget of the exactness
    #                            certificate pins this on canonical
    #                            topologies (tests/test_exact_prefix.py).


def _stage_select(stage: jnp.ndarray, n_stages: int, conns: jnp.ndarray,
                  rev: jnp.ndarray) -> jnp.ndarray:
    """(N, C, S+1) one-hot of each neighbor slot's stage id. The naive
    2-index gather lat[stage[p], stage[conns[p,i]]] costs ~60 ms at 100k
    (scalar gathers); instead: pull each neighbor's stage id through the
    reverse map (ops/pull.py) and build a fused one-hot over the S+1-wide
    stage axis — all vectorized."""
    stage_iota = jnp.arange(n_stages, dtype=jnp.float32)
    stage_q = neighbor_pull_min(stage.astype(jnp.float32), conns, rev)
    return stage_q[..., None] == stage_iota


def edge_tables(stage, lat_ms, conns, rev, loss_stage=None):
    """Precompute the per-slot stage-pair tables disseminate() needs:
    lat_edge[p, i] = lat_ms[stage[p], stage[conns[p, i]]] (0 on pads) and,
    when loss_stage is given, the same contraction of the loss matrix.

    These are LOOP-INVARIANT ACROSS PUBLISHES (graph and topology are
    experiment constants) but were being rebuilt inside every disseminate
    call — 71.8 ms/publish at 100k peers, measured r4. The Simulator
    computes them once per experiment and passes them through
    disseminate(lat_edge=..., loss_edge=...); direct callers that skip
    them get the identical in-call fallback."""
    sel = _stage_select(stage, lat_ms.shape[0], conns, rev)
    lat_edge = jnp.where(sel, lat_ms[stage][:, None, :], 0.0).sum(axis=-1)
    loss_edge = None
    if loss_stage is not None:
        loss_edge = jnp.where(
            sel, loss_stage[stage][:, None, :], 0.0).sum(axis=-1)
    return lat_edge, loss_edge


@struct.dataclass
class AnswerTables:
    """Lat-sorted views of the connection slots — the static service order
    of the serialized answer-queue fold (gossip_fold). Like edge_tables,
    these depend only on (lat_edge, conns): experiment constants rebuilt
    inside every publish until r6 — two stable (N, C) argsorts plus two
    take_alongs per message at the 100k bench shape, a measured slice of
    the accounting_s regression. Build once with answer_tables() and pass
    through disseminate(ans_tables=...); row-aligned, so a sharded run
    reshards them with the other edge constants."""

    perm_lat: jnp.ndarray     # (N, C) int32 lat-ascending slot permutation
    inv_lat: jnp.ndarray      # (N, C) int32 its inverse
    lat_sorted: jnp.ndarray   # (N, C) f32 slot latency in that order, INF pads
    conns_sorted: jnp.ndarray  # (N, C) int32 neighbor ids in that order


def answer_tables(lat_edge, conns) -> AnswerTables:
    """Precompute the lat-sort tables of the answer fold (see AnswerTables)."""
    slot_lat = jnp.where(conns >= 0, lat_edge, INF)
    perm_lat = jnp.argsort(slot_lat, axis=-1, stable=True)
    inv_lat = jnp.argsort(perm_lat, axis=-1, stable=True)
    return AnswerTables(
        perm_lat=perm_lat,
        inv_lat=inv_lat,
        lat_sorted=jnp.take_along_axis(slot_lat, perm_lat, axis=-1),
        conns_sorted=jnp.take_along_axis(conns, perm_lat, axis=-1),
    )


def _ranks_f32(priority: jnp.ndarray) -> jnp.ndarray:
    return jnp.argsort(jnp.argsort(priority, axis=-1), axis=-1).astype(jnp.float32)


def _mask_count_smallest(prio: jnp.ndarray, count: jnp.ndarray) -> jnp.ndarray:
    """Row mask of the `count[i]` smallest entries: rank(prio) < count
    without materializing ranks — one VALUE sort plus a per-row threshold
    gather instead of _ranks_f32's double key+payload argsort (the gossip
    sampler runs this once per mcache round, so the bench shape paid six
    argsorts per publish here). Fractional counts select ceil(count)
    entries, matching integer-rank < count. Strict < at the threshold
    drops boundary ties — for continuous uniform priorities a measure-zero
    deviation from the rank formulation (at worst one fewer sample drawn
    in an f32-collision row)."""
    c_ = prio.shape[-1]
    kk = jnp.ceil(count).astype(jnp.int32)
    s = jnp.sort(prio, axis=-1)
    thresh = jnp.take_along_axis(
        s, jnp.clip(kk, 0, c_ - 1)[:, None], axis=-1)
    thresh = jnp.where(kk[:, None] >= c_, INF, thresh)
    return prio < thresh


def _next_heartbeat(t, phase, hb_ms):
    """First heartbeat tick of a peer strictly after time t (per-peer phase —
    nodes start at different wall times, so ticks are unaligned)."""
    return (jnp.floor((t - phase) / hb_ms) + 1.0) * hb_ms + phase


@partial(
    jax.jit,
    static_argnames=("params", "payload_bytes", "fragments", "with_gossip",
                     "mesh", "with_fanout", "return_plan", "loss_mode"),
)
def disseminate(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    stage: jnp.ndarray,
    lat_ms: jnp.ndarray,
    bw_up_mbit_per_stage: jnp.ndarray,
    publisher,
    t0_ms,
    params: SimParams,
    payload_bytes: int,
    fragments: int = 1,
    with_gossip: bool = True,
    mesh=None,
    loss_stage=None,
    with_fanout: bool = False,
    return_plan: bool = False,
    bw_down_mbit_per_stage=None,
    loss_mode: str = "tcp",
    lat_edge=None,
    loss_edge=None,
    ans_tables=None,
    valid_edge=None,
    censor_edge=None,
):
    """Propagate one application message (all fragments) through the mesh.

    Returns (DisseminationResult, new_state). new_state carries advanced RNG,
    firstMessageDeliveries credit, and byte/duplicate counters.

    The fixpoint itself runs receiver-side (parallel/exchange.py): per-edge
    constants are gathered once, then each iteration touches only the (N,)
    arrival-time vector. With `mesh` (a 1-D jax.sharding.Mesh over the peer
    axis) the iteration runs under shard_map — one t_rx all-gather + one
    convergence-bit psum per iteration over ICI; without it, the same
    expression on one device.

    `loss_stage`: optional (S+1, S+1) per-stage-pair packet-loss rate
    (topogen's packet_loss edges, shadow/topogen.py:21,56). Pass None
    (not an all-zero matrix) for the lossless fast path. Two models,
    selected by `loss_mode`:

      "tcp" (default, Shadow-faithful): nodes under Shadow run real TCP
      stacks over
      the lossy edges (regression/Dockerfile_amd64_shadow:3-11), so loss
      becomes LATENCY — the copy is redelivered after a geometric number
      of RTO-doubling retransmissions (constants above). Coverage stays
      ~1.0 and p99 inflates, which is what a lossy topogen `-l` run of the
      reference measures.

      "message" (QUIC-unreliable-style): each directed edge
      independently fails to carry the whole message with its loss
      probability; mesh redundancy then degrades coverage gracefully.
      Kept for studying datagram-transport behavior and as the coverage
      stressor the gossip-recovery tests use.

    Either way a lost/delayed copy keeps its uplink queue slot and its
    tx-byte accounting — the transmission happened.

    `return_plan`: additionally return the message's sampled "plan" — the
    send sets, rank priorities, per-round gossip targets, loss survivals,
    phases and uplink occupancy this call drew — as a third output. This is
    the seam for the independent discrete-event cross-check
    (tests/test_des_crosscheck.py): the DES replays the exact same model
    inputs through an event queue written independently of the fixpoint.

    `with_fanout`: the publisher is NOT subscribed to the topic (gossipsub
    v1.1 fanout publish). It sends to its persistent fanout set — up to D
    connected topic peers, reused across publishes and topped back up to D
    at each publish (replenishFanout's effect at the moment it matters),
    expiring fanout_ttl_ms after the last fanout publish (heartbeat_step
    drops expired sets). With flood_publish the publisher floods all topic
    peers as usual, but the fanout set is still maintained, matching
    nim-libp2p's publish() which updates fanout in the unsubscribed branch
    regardless of floodPublish. The caller decides with_fanout from the
    publisher's subscription (host-side; subscription is publish-path
    static), keeping the subscribed-publisher compile unchanged.
    """
    n, c = conns.shape
    extra = (1 if loss_stage is not None else 0) + (1 if with_fanout else 0)
    keys = jax.random.split(state.key, 3 + extra)
    key, k_rank, k_gossip = keys[0], keys[1], keys[2]
    nxt = 3
    if loss_stage is not None:
        k_loss = keys[nxt]
        nxt += 1
    if with_fanout:
        k_fan = keys[nxt]

    frag_bytes = max(payload_bytes // fragments, 16)
    tx_ms = (frag_bytes * 8.0) / (bw_up_mbit_per_stage[stage] * 1e6) * 1e3  # (N,)
    # receiver-side drain time of one copy on each peer's downlink. The
    # reference topology sets host_bandwidth_down == host_bandwidth_up per
    # stage (shadow/topogen.py:50-51); pass bw_down_mbit_per_stage to model
    # asymmetric links.
    bw_down = (bw_up_mbit_per_stage if bw_down_mbit_per_stage is None
               else bw_down_mbit_per_stage)
    rx_ms = (frag_bytes * 8.0) / (bw_down[stage] * 1e6) * 1e3          # (N,)
    # downlink clamp for THIS message's first delivery: nothing completes at
    # q before q's downlink drains earlier messages plus this copy
    rx_const = state.rx_free_ms + rx_ms                                # (N,)

    # per-slot link latency lat[stage[p], stage[conns[p,i]]] (and the loss
    # contraction when needed): experiment constants — callers that loop
    # over publishes precompute them via edge_tables(); the fallback here
    # keeps one-shot calls self-contained. NOTE: the stage pull runs once
    # at top level, OUTSIDE the fragment vmap — batch_factor stays 1 (the
    # vmapped pulls below pass fragments).
    if lat_edge is None or (loss_stage is not None and loss_edge is None):
        lat_edge_c, loss_edge_c = edge_tables(
            stage, lat_ms, conns, rev, loss_stage)
        if lat_edge is None:
            lat_edge = lat_edge_c                         # (N, C); 0 on pads
        if loss_edge is None:
            loss_edge = loss_edge_c

    # forwarding targets: mesh members; the publisher flood-publishes to every
    # connected topic peer (main.nim:279). The neighbor alive&subscribed
    # pull is publish-invariant between membership changes — callers that
    # loop over publishes precompute it (Simulator/bench maintain it and
    # invalidate on churn or subscription flips), saving one full
    # row-gather pass per publish. DYNAMIC-GRAPH CONTRACT: a hoisted
    # valid_edge (and lat_edge/loss_edge/ans_tables) is a pure function of
    # conns/rev — if the repair controller's dial path extended the graph
    # (ops/repair.py), the caller must re-derive all of them against the
    # mutated arrays (Simulator.rebind_graph) and the warm-start carry in
    # state.warm_offset_ms must already be INF (repair_round writes it on
    # any committed dial); passing stale tables here silently publishes
    # over the pre-repair edge set.
    has = conns >= 0
    if valid_edge is not None:
        valid = valid_edge
    else:
        valid = has & neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev)
    # v1.1 score thresholds (nim-libp2p defaults; the reference comments the
    # overrides out, main.nim:276-278). With the default non-negative score
    # weights no peer can score below any threshold, so the whole block is
    # statically absent from the compiled step.
    thresholds_can_bind = params.slow_weight < 0.0 or params.fmd_weight < 0.0
    if thresholds_can_bind:
        sc = state.score(params)                       # my score of each nbr
        pub_ok = sc >= params.publish_threshold        # flood/fanout gate
        # graylist: the RECEIVER ignores traffic from peers it scores below
        # the threshold — pulled to the sender side it gates DELIVERY only
        # (the send still happens and is accounted), which is exactly the
        # `survive` semantics shared with packet loss below
        gray_ok = reciprocal_pull_bool(
            sc >= params.graylist_threshold, conns, rev)
    if loss_mode not in ("message", "tcp"):
        raise ValueError(f"unknown loss_mode {loss_mode!r}")
    retx_ms = None
    if loss_stage is not None:
        # one independent draw per (FRAGMENT, directed edge): each fragment
        # is a distinct GossipSub message upstream (main.nim:177-179 flips
        # the fragment byte precisely so the msgId hash differs), so its
        # packets face the lossy link independently — correlated
        # per-message draws would black out every fragment of a message on
        # an unlucky edge at once, which no packet-loss process does.
        # Memory note: the draws (and the derived retx/lat_deliver) are
        # (F, N, C) and live through the whole fragment vmap — generating
        # them inside the per-fragment body would not lower the peak,
        # since vmap batches all lanes anyway. At 1M peers this is
        # ~0.4 GB per f32 array per fragment; lossy runs at extreme N
        # should keep FRAGMENTS modest (the five BASELINE configs that
        # reach 1M are lossless and never allocate any of this).
        if loss_mode == "tcp":
            # geometric retransmission count per edge (see the model
            # constants above): P(j >= k) = p^k via the inverse-CDF
            # j = floor(log u / log p); j > MAX_RETRIES abandons the copy
            u = jnp.clip(jax.random.uniform(k_loss, (fragments, n, c)),
                         1e-12)
            safe_p = jnp.clip(loss_edge, 1e-9, 1.0 - 1e-9)
            j = jnp.where(
                loss_edge > 0.0,
                jnp.floor(jnp.log(u) / jnp.log(safe_p)),
                0.0,
            )
            j = jnp.minimum(j, float(MAX_RETRIES + 1))
            survive = j <= float(MAX_RETRIES)
            rto = jnp.maximum(RTO_MIN_MS, 1.5 * 2.0 * lat_edge)
            retx_ms = jnp.where(
                survive & (j > 0.0), rto * (jnp.exp2(j) - 1.0), 0.0)
        else:
            # whole-copy loss (see docstring): `survive` gates DELIVERY
            # only — a lost copy was still transmitted, so it keeps its
            # uplink queue slot and its tx-byte accounting; it just never
            # arrives
            survive = (jax.random.uniform(k_loss, (fragments, n, c))
                       >= loss_edge)
    else:
        survive = None
    # keep the loss-only draw separate from the graylist gate: lost_tx
    # counts copies the NETWORK dropped, and a receiver-side graylist
    # ignore is not a network loss (the bytes arrived and were discarded
    # above the transport) — folding gray_ok into the counter inflated
    # "network-lost" copies whenever the graylist was active
    survive_loss = survive
    if thresholds_can_bind:
        survive = gray_ok if survive is None else survive & gray_ok
    if censor_edge is not None:
        # adversarial per-edge DROP mask (ops/adversary.py): an in-mesh
        # censor silently withholds the copy. Same delivery-only semantics
        # as the graylist gate — and same exclusion from survive_loss, so
        # lost_tx keeps counting copies the NETWORK dropped. None (the
        # default pytree structure) keeps benign traces bit-identical.
        survive = (~censor_edge if survive is None
                   else survive & ~censor_edge)
    is_pub = jnp.arange(n) == publisher
    if with_fanout:
        # fanout set: still-valid unexpired members, topped back up to D
        # with fresh draws from the remaining connected topic peers. Computed
        # for every row (shape-static) but only the publisher's row is used
        # or written back.
        fan_active = (state.fanout_mask & valid
                      & (state.fanout_expire[:, None] > t0_ms))
        if thresholds_can_bind:
            # the v1.1 heartbeat drops fanout members scoring below
            # publishThreshold; checking at publish time is equivalent at
            # the moment it matters (same treatment as replenishment)
            fan_active = fan_active & pub_ok
        need_fan = jnp.maximum(
            float(params.d) - fan_active.sum(axis=-1).astype(jnp.float32), 0.0)
        fan_cand = valid & ~fan_active
        if thresholds_can_bind:
            fan_cand = fan_cand & pub_ok  # fanout selection skips low scorers
        fprio = jnp.where(fan_cand, jax.random.uniform(k_fan, (n, c)), INF)
        fan_row = fan_active | (
            fan_cand & (_ranks_f32(fprio) < need_fan[:, None]))

    tgt = state.mesh_mask & valid
    flood_set = valid
    if thresholds_can_bind:
        # publish (flood and fanout selection) skips peers the publisher
        # scores below publishThreshold
        flood_set = valid & pub_ok
    if with_fanout:
        pub_tgt = flood_set if params.flood_publish else fan_row
        tgt = jnp.where(is_pub[:, None], pub_tgt, tgt)
    elif params.flood_publish:
        tgt = jnp.where(is_pub[:, None], flood_set, tgt)

    # randomized send order per peer (one draw per message, standing in for
    # the reference's per-peer queue service order)
    rprio = jnp.where(tgt, jax.random.uniform(k_rank, (n, c)), INF)

    # gossip edge sampling: non-mesh connected topic peers; count =
    # max(D_lazy, gossip_factor * |candidates|)  (v1.1 heartbeat gossip).
    # The reference gossips EVERY heartbeat over the mcache history window
    # (history_gossip rounds, main.nim:259,283): each tick draws a FRESH
    # sample, so a peer missed in round h can be reached in round h+1 —
    # that re-sampling is what drives gossip recovery under loss/churn.
    g_cand = valid & ~tgt
    if thresholds_can_bind:
        # no IHAVE to peers scored below gossipThreshold
        g_cand = g_cand & (sc >= params.gossip_threshold)
    n_gc = g_cand.sum(axis=-1).astype(jnp.float32)
    g_count = jnp.maximum(float(params.d_lazy), params.gossip_factor * n_gc)
    n_rounds = params.history_gossip if with_gossip else 1
    gkeys = jax.random.split(k_gossip, n_rounds)
    g_tgt_w = jnp.stack([
        g_cand & _mask_count_smallest(
            jnp.where(g_cand, jax.random.uniform(gkeys[h], (n, c)), INF),
            g_count)
        for h in range(n_rounds)
    ])                                                  # (W, N, C)
    g_tgt = g_tgt_w.any(axis=0)
    # round offsets grow by a heartbeat each, so only the FIRST round an edge
    # is sampled can be its min offer — the multi-round term collapses to a
    # single (N, C) per-edge heartbeat offset inside the fixpoint (the full
    # per-round sets are still used for IHAVE/IWANT accounting below)
    g_off = jnp.min(
        jnp.where(g_tgt_w,
                  jnp.arange(n_rounds, dtype=jnp.float32)[:, None, None],
                  jnp.float32(n_rounds)),
        axis=0) * params.heartbeat_ms
    # heartbeat phase is a persistent per-NODE property (drawn once per run in
    # init_state), so gossip-arrival timing is consistent across messages
    hb_phase = state.hb_phase

    can_send = state.alive & state.subscribed
    if with_fanout:
        # the unsubscribed publisher originates (and gossips about) the
        # message even though it is not a topic member
        can_send = can_send | (is_pub & state.alive)

    # cross-message bandwidth contention: a sender's queue for THIS message
    # starts no earlier than the time its uplink drains traffic of earlier
    # messages (state write-back below; reference per-connection queues
    # serialize all in-flight traffic, main.nim:264-299)
    uplink = state.uplink_free_ms

    # effective per-edge delivery latency: the wire latency, times the TCP
    # slow-start flight count of the data transfer (tcp_flights above: a
    # transfer needing F cold-start flights pays F-1 extra RTTs = 2*lat
    # each), plus (tcp loss mode) the sampled retransmission stall.
    # Control messages (IHAVE/IWANT/IDONTWANT timing checks) keep the bare
    # lat_edge — they are single small packets inside the first window.
    # Mesh fragment f rides a connection the f earlier fragments of the
    # same back-to-back stream already warmed: its last byte departs in
    # flight F((f+1)*frag_bytes) of the cold-started stream. A gossip
    # answer is a single cold transfer — the non-mesh edge idled since the
    # previous message, so its window restarted. (Retransmission stalls
    # and flight counts compose additively; a real RTO inside slow start
    # would also halve the window — a second-order interaction left out.)
    ss_mesh = tuple(
        float(tcp_flights((f + 1) * frag_bytes, params) - 1)
        for f in range(fragments))
    ss_ans = float(tcp_flights(frag_bytes, params) - 1)
    ss_scale = jnp.asarray([1.0 + 2.0 * e for e in ss_mesh], jnp.float32)
    ans_scale = jnp.float32(1.0 + 2.0 * ss_ans)

    def _frag_slice(x, frag_idx):
        """Per-fragment view of a possibly-(F, N, C) array. Loss/retx draws
        are per fragment (leading axis); graylist-only survive masks are
        (N, C), shared across fragments."""
        if x is None or x.ndim == 2:
            return x
        return x[frag_idx.astype(jnp.int32)]

    def _ld_mesh(frag_idx):
        """Mesh-edge delivery latency of this fragment (slow-start flights
        x wire latency + sampled retransmission stall)."""
        ld = lat_edge * ss_scale[frag_idx.astype(jnp.int32)]
        r = _frag_slice(retx_ms, frag_idx)
        return ld if r is None else ld + r

    def _ld_ans(frag_idx):
        """Gossip-answer delivery latency (cold-start flights; same
        per-edge retransmission draw as the mesh copy — one draw per
        (fragment, edge), a documented approximation: the answer is a
        rare duplicate of data the mesh already moved, so an independent
        re-draw would change only the tail of a tail)."""
        ld = lat_edge * ans_scale
        r = _frag_slice(retx_ms, frag_idx)
        return ld if r is None else ld + r

    # ---- serialized gossip-answer machinery --------------------------------
    # Static service order for the per-round queue fold: within a round all
    # of a sender's IWANTs arrive at A_h + 2*lat (A_h shared per sender-
    # round), so arrival order IS lat order — a permutation of each row
    # that never changes across fragments, phases or estimates. Sorting
    # once here turns every fold into elementwise work plus within-row
    # take_along gathers (the r5 bench catch: per-estimate global argsorts
    # cost more than the whole r4 publish). The sort itself is an
    # EXPERIMENT constant (lat_edge + conns only): callers that loop over
    # publishes precompute it via answer_tables() — the in-call fallback
    # keeps one-shot calls self-contained (same contract as edge_tables).
    if with_gossip:
        if ans_tables is None:
            ans_tables = answer_tables(lat_edge, conns)
        perm_lat = ans_tables.perm_lat                           # (N, C)
        inv_lat = ans_tables.inv_lat
        lat_sorted = ans_tables.lat_sorted
        conns_sorted = ans_tables.conns_sorted
        gw_sorted = [
            jnp.take_along_axis(g_tgt_w[h], perm_lat, axis=-1)
            for h in range(n_rounds)
        ]

    def _sorted_frag(x, frag_idx):
        """Per-fragment slice of a (F/None, N, C) array, in lat order."""
        xs = _frag_slice(x, frag_idx)
        return None if xs is None else jnp.take_along_axis(
            xs, perm_lat, axis=-1)

    def _round_req(h, tick, live, q_t, lat, gw_h, sv):
        """THE request/announce semantics of the serialized answer model,
        shared verbatim by the lat-sorted fold and the global-sort exact
        path (one copy, per the r5 review): round h's IHAVE leaves at
        A_h = max(tick + h*hb, uplink); a sampled live edge is REQUESTED
        iff the receiver still lacks the message when the IHAVE lands
        (strictly q_t > A_h + lat), and a lossy edge loses the IHAVE with
        the copy (survive-gated), so no IWANT ever comes back on it.
        Returns (a_h (N,1), sampled, requested) in the caller's layout."""
        a_h = jnp.maximum(
            tick + h * params.heartbeat_ms, uplink)[:, None]
        samp = gw_h & live[:, None]
        req = samp & (q_t > a_h + lat)
        if sv is not None:
            req = req & sv
        return a_h, samp, req

    def gossip_fold(t_rx, frag_idx):
        """Exact serialized gossip-answer offers via the per-round fold.

        A peer answering several IWANTs serializes the answers on its
        uplink — the reference's per-connection queues all feed the host's
        single host_bandwidth_up under Shadow (main.nim:264-299,
        shadow/topogen.py:50-51) — a single-server queue in IWANT-arrival
        order, rounds chaining through the carried busy time. Processing
        round-by-round in the static lat order is EXACT as long as rounds
        don't interleave (a round's last requested arrival precedes the
        next round's first — true whenever the heartbeat exceeds the
        round-trip spread, i.e. always at reference heartbeats); the fold
        detects the interleaved corner and reports it in `mixed`, which
        routes the message to the global-sort slow path. Only requested
        jobs (receiver still lacking at the IHAVE, survive-gated) occupy
        the queue; every sampled edge still gets an offer — the time its
        answer WOULD arrive if requested — which is self-consistent
        because an offer can only bind for a still-lacking receiver.

        Returns (g_abs, req_any, drain, mixed, wait_max): per-edge
        absolute offers (INF where no sampled live edge), answered flags,
        per-peer answer queue drain (0 if none), the scalar interleave
        flag, and the scalar MAX WAIT any requested answer spent queued
        behind another (serve - arrival) — the per-hop error bound of the
        bounded delivery mode (serialize_answers=False)."""
        base = t_rx + params.proc_delay_ms
        tick = _next_heartbeat(base, hb_phase, params.heartbeat_ms)  # (N,)
        live = can_send & (t_rx < INF)
        sv_s = _sorted_frag(survive, frag_idx)
        retx_s = _sorted_frag(retx_ms, frag_idx)
        lda_s = lat_sorted * ans_scale
        if retx_s is not None:
            lda_s = lda_s + retx_s
        q_t_s = t_rx[jnp.clip(conns_sorted, 0)]   # receiver times, lat order
        txp = tx_ms[:, None]
        busy = uplink                               # (N,) queue busy carry
        g_sorted = jnp.full((n, c), INF)
        req_any_s = jnp.zeros((n, c), bool)
        had_req = jnp.zeros((n,), bool)
        mixed = jnp.bool_(False)
        wait_max = jnp.float32(0.0)
        prev_max_w = jnp.full((n,), -INF)
        for h in range(n_rounds):
            a_h, samp, req = _round_req(
                h, tick, live, q_t_s, lat_sorted, gw_sorted[h], sv_s)
            w = a_h + 2.0 * lat_sorted              # INF on pads/late slots
            # interleave check: this round's earliest requested arrival vs
            # the previous round's latest
            min_w = jnp.where(req, w, INF).min(axis=-1)
            mixed = mixed | jnp.any(min_w < prev_max_w - 1e-4)
            prev_max_w = jnp.maximum(
                prev_max_w, jnp.where(req, w, -INF).max(axis=-1))
            rf = req.astype(jnp.float32)
            R = jnp.cumsum(rf, axis=-1)
            m_term = jnp.where(req, w - (R - 1.0) * txp, -INF)
            M = jax.lax.cummax(m_term, axis=m_term.ndim - 1)
            M_prev = jnp.concatenate(
                [jnp.full_like(M[:, :1], -INF), M[:, :-1]], axis=-1)
            R_prev = jnp.concatenate(
                [jnp.zeros_like(R[:, :1]), R[:, :-1]], axis=-1)
            serve = jnp.maximum(
                w, jnp.maximum(busy[:, None], M_prev) + R_prev * txp)
            offer = serve + txp + lda_s
            g_sorted = jnp.minimum(g_sorted, jnp.where(samp, offer, INF))
            wait_max = jnp.maximum(
                wait_max, jnp.where(req, serve - w, 0.0).max())
            req_any_s = req_any_s | req
            r_last = R[:, -1]
            busy = jnp.where(
                r_last > 0.0,
                jnp.maximum(busy, M[:, -1]) + r_last * tx_ms, busy)
            had_req = had_req | (r_last > 0.0)
        g_abs = jnp.take_along_axis(g_sorted, inv_lat, axis=-1)
        g_abs = jnp.where(g_abs < INF, g_abs, INF)  # overflow -> sentinel
        req_any = jnp.take_along_axis(req_any_s, inv_lat, axis=-1)
        drain = jnp.where(had_req, busy, 0.0)
        return g_abs, req_any, drain, mixed, wait_max

    def _gossip_jobs(t_rx, frag_idx):
        """Shared job builder of the serialized answer model: per sampled
        (round h, slot i) job, its IWANT arrival W = announce departure +
        2 link traversals, and whether it is REQUESTED — the receiver
        still lacks the message when that round's IHAVE lands (a lossy
        edge loses the IHAVE with the copy: one survive draw per
        fragment-edge, so no IWANT ever comes back on it)."""
        base = t_rx + params.proc_delay_ms
        tick = _next_heartbeat(base, hb_phase, params.heartbeat_ms)  # (N,)
        live = can_send & (t_rx < INF)
        sv = _frag_slice(survive, frag_idx)
        q_t = t_rx[jnp.clip(conns, 0)]           # (N, C) receiver times
        Ws, reqs = [], []
        for h in range(n_rounds):
            a_h, samp, r_h = _round_req(
                h, tick, live, q_t, lat_edge, g_tgt_w[h], sv)
            Ws.append(jnp.where(samp, a_h + 2.0 * lat_edge, INF))
            reqs.append(r_h)
        Wf = jnp.concatenate(Ws, axis=-1)        # (N, H*C), col = h*C + i
        rf = jnp.concatenate(reqs, axis=-1)
        return Wf, rf

    def _offers_from_serve(serve_u, frag_idx):
        """Per-edge delivery offer from per-job serve starts: + one tx
        serialization + the answer's cold-flight delivery latency; min
        over the edge's sampled rounds."""
        lda = _ld_ans(frag_idx)
        serve_hni = serve_u.reshape(n, n_rounds, c)
        g_abs = jnp.min(
            serve_hni + tx_ms[:, None, None] + lda[:, None, :], axis=1)
        # overflowed INF+finite arithmetic back to the sentinel
        return jnp.where(g_abs < INF, g_abs, INF)

    def gossip_serial_exact(t_rx, frag_idx):
        """Exact serialized gossip-answer offers at the estimate t_rx.

        A peer answering several IWANTs serializes the answers on its
        uplink — the reference's per-connection queues all feed the
        host's single host_bandwidth_up under Shadow (main.nim:264-299,
        shadow/topogen.py:50-51) — so the answers form a single-server
        queue in IWANT-arrival order (ties broken by (round, slot),
        matching the DES heap). Only requested jobs occupy the queue, but
        every sampled edge gets an offer = the time its answer WOULD
        arrive if requested (self-consistent: an offer can only bind for
        a receiver that was still lacking, i.e. requesting).

        Single-server queue fold in global W order (rounds chain
        naturally: a round's backlog spills into the next through the
        running busy time). For sorted arrivals the busy time after
        position j is B_j = M_j + R_j*tx with R the requested prefix
        count and M_j = cummax(W - (R-1)*tx over requested prefix); the
        job at position j starts at max(W_j, B_{j-1}).

        Returns (g_abs, req_any, drain). Runs the sorts unconditionally —
        callers reach it only on the hint-gated slow branch."""
        Wf, rf_b = _gossip_jobs(t_rx, frag_idx)
        req_any = rf_b.reshape(n, n_rounds, c).any(axis=1)
        rf = rf_b.astype(jnp.float32)
        txp = tx_ms[:, None]
        perm = jnp.argsort(Wf, axis=-1, stable=True)
        ws = jnp.take_along_axis(Wf, perm, axis=-1)
        rs = jnp.take_along_axis(rf, perm, axis=-1)
        R = jnp.cumsum(rs, axis=-1)
        m_term = jnp.where(rs > 0.0, ws - (R - 1.0) * txp, -INF)
        M = jax.lax.cummax(m_term, axis=m_term.ndim - 1)
        M_prev = jnp.concatenate(
            [jnp.full_like(M[:, :1], -INF), M[:, :-1]], axis=-1)
        R_prev = jnp.concatenate(
            [jnp.zeros_like(R[:, :1]), R[:, :-1]], axis=-1)
        serve = jnp.maximum(ws, M_prev + R_prev * txp)
        inv = jnp.argsort(perm, axis=-1, stable=True)
        serve_u = jnp.take_along_axis(serve, inv, axis=-1)
        drain = jnp.where(
            R[:, -1] > 0.0, M[:, -1] + R[:, -1] * tx_ms, 0.0)
        return _offers_from_serve(serve_u, frag_idx), req_any, drain

    def offers(t_rx, rank, k_p, frag_idx, send_mask, deliver_only=False,
               g_abs=None):
        """Arrival-time offers made by every peer on every neighbor slot.
        `deliver_only`: additionally mask copies the network loses — use for
        anything receiver-side (first-sender detection, delivery pulls);
        leave False for transmit-side accounting (sends, tx bytes).
        `g_abs`: the serialized gossip-answer offers of gossip_fold /
        gossip_serial_exact evaluated at the SAME t_rx (required when with_gossip)."""
        base = t_rx + params.proc_delay_ms
        start = jnp.maximum(base, uplink)
        ld = _ld_mesh(frag_idx)
        # uplink serialization: (rank+1) sends of this fragment, plus the
        # frag_idx earlier fragments each occupying k_p uplink slots
        queue = (rank + 1.0 + frag_idx * k_p[:, None]) * tx_ms[:, None]
        cand = start[:, None] + queue + ld
        live = can_send[:, None] & (t_rx[:, None] < INF)
        sm = send_mask
        if deliver_only and survive is not None:
            sv = _frag_slice(survive, frag_idx)
            sm = sm & sv
        cand = jnp.where(sm & live, cand, INF)
        if with_gossip:
            ga = g_abs
            if deliver_only and survive is not None:
                ga = jnp.where(sv, ga, INF)
            cand = jnp.minimum(cand, ga)
        return cand

    def pull(cand):
        """incoming[q, j] = offer made to q by the neighbor in its slot j
        (row-gather + fused slot select; see ops/pull.py for why). Runs
        inside the fragment vmap, so the memory dispatch must see the
        fragment multiplicity."""
        return reciprocal_pull_min(cand, conns, rev, batch_factor=fragments)

    def _converge_dyn(rank, k_p, frag_idx, t_pub, send_mask, t_init=None):
        """UNSERIALIZED fixpoint (every gossip answer rides its own uplink
        slot — exact whenever no answer queue forms; converge() below
        detects and repairs the rare serialized case). `t_init`: optional
        warm start — a pointwise upper bound on the true arrival times
        converges to the same unique fixpoint (Bellman-Ford from above,
        non-negative edge costs). A HEURISTIC seed (the cross-publish warm
        carry) may undershoot and stick; callers verify the returned
        self-consistency certificate (see phases_fast) and fall back cold.

        Returns (t, inc, ok): the fixpoint, the deliver-only incoming-
        offer matrix of the loop's LAST pass — the no-change confirmation
        pass evaluates it at the final times, so the matrix the first-
        sender attribution and the certificate need rides out of the loop
        for FREE instead of costing another offers()+pull — and the
        convergence bit (False = the iteration cap cut the loop and `inc`
        is one pass stale)."""
        t0 = (jnp.full((n,), INF) if t_init is None else t_init
              ).at[publisher].set(t_pub)
        # arrival times are about DELIVERY: lost copies never relax an edge
        # (their queue slots still count — rank/k_p came from the unmasked
        # send set)
        sv = _frag_slice(survive, frag_idx)
        ld = _ld_mesh(frag_idx)
        deliver = send_mask if sv is None else send_mask & sv
        g_deliver = g_tgt if sv is None else g_tgt & sv
        if mesh is not None:
            # sharded: receiver-local constants, one (N,) all-gather + one
            # psum per iteration over ICI (parallel/exchange.py)
            c = build_recv_constants(
                conns, rev, lat_edge, tx_ms, rank, k_p, frag_idx, deliver,
                can_send, g_deliver, g_off, hb_phase, uplink, rx_const,
                params.proc_delay_ms, params.heartbeat_ms, with_gossip,
                lat_deliver=ld, ld_gossip=_ld_ans(frag_idx),
                packed=params.packed_state,
            )
            return converge_sharded(t0, c, params.max_relax_iters, mesh)
        if exceeds_budget(jnp.float32, conns.shape, fragments):
            # large N (1M-peer class): the row-gather pull would blow the
            # memory budget and its 2-index fallback costs ~0.7 s/iteration —
            # switch to the receiver-side constant formulation: per-edge
            # constants gathered ONCE, then each iteration is (N, C)
            # elementwise plus one gather of the (N,) time vector (a 4 MB
            # table at 1M peers vs a 160 MB one), the same expression the
            # sharded path runs.
            c = build_recv_constants(
                conns, rev, lat_edge, tx_ms, rank, k_p, frag_idx, deliver,
                can_send, g_deliver, g_off, hb_phase, uplink, rx_const,
                params.proc_delay_ms, params.heartbeat_ms, with_gossip,
                lat_deliver=ld, ld_gossip=_ld_ans(frag_idx),
                packed=params.packed_state,
            )
            return converge_recv(t0, c, params.max_relax_iters)
        # single device below the budget: sender-major offers (loop-invariant
        # parts hoisted here), row-gather pull per iteration — ~2.5x the
        # per-iteration speed of a receiver-side index gather (ops/pull.py)
        queue = (rank + 1.0 + frag_idx * k_p[:, None]) * tx_ms[:, None]
        a_base = jnp.where(
            deliver & can_send[:, None], queue + ld, INF)
        g_base = jnp.where(
            g_deliver & can_send[:, None],
            2.0 * lat_edge + _ld_ans(frag_idx) + tx_ms[:, None], INF)

        def cond(carry):
            _, _, changed, it = carry
            return changed & (it < params.max_relax_iters)

        def body(carry):
            t_rx, _, _, it = carry
            live = (t_rx < INF)[:, None]
            base = t_rx + params.proc_delay_ms
            start = jnp.maximum(base, uplink)
            cand = jnp.where(live, start[:, None] + a_base, INF)
            if with_gossip:
                hb = _next_heartbeat(base, hb_phase, params.heartbeat_ms)
                cand = jnp.minimum(
                    cand,
                    jnp.where(live,
                              jnp.maximum(hb[:, None] + g_off,
                                          uplink[:, None]) + g_base, INF))
            inc = pull(cand)
            # downlink clamp (max distributes over the row min, so clamping
            # the min equals clamping every candidate)
            t_new = jnp.minimum(
                t_rx, jnp.maximum(inc.min(axis=-1), rx_const))
            return t_new, inc, jnp.any(t_new < t_rx), it + 1

        # (a mesh-only pre-relaxation before the full loop was measured
        # NET-WORSE here r4: the per-iteration cost is pull-dominated, so
        # skipping the gossip candidate arithmetic saves little while the
        # extra warm-up iterations add whole pulls)
        # iteration counter carries a STRONG int32: a Python-int carry is
        # weak-typed and re-promotes on feed-back (graft-audit GA-J002)
        t_rx, inc, changed, _ = jax.lax.while_loop(
            cond, body,
            (t0, jnp.full(conns.shape, INF), jnp.bool_(True), jnp.int32(0)))
        return t_rx, inc, ~changed

    def _converge_floor(rank, k_p, frag_idx, t_pub, send_mask, g_floor,
                        t_init):
        """Mesh-only fixpoint against a FROZEN per-receiver gossip floor
        (the serialized answer offers of one outer pass, already pulled to
        the receiver side and row-minimized). Same three path dispatches as
        _converge_dyn, with the gossip arithmetic out of the loop body."""
        t0 = t_init.at[publisher].set(t_pub)
        sv = _frag_slice(survive, frag_idx)
        ld = _ld_mesh(frag_idx)
        deliver = send_mask if sv is None else send_mask & sv
        if mesh is not None or exceeds_budget(jnp.float32, conns.shape,
                                              fragments):
            c = build_recv_constants(
                conns, rev, lat_edge, tx_ms, rank, k_p, frag_idx, deliver,
                can_send, g_tgt, g_off, hb_phase, uplink, rx_const,
                params.proc_delay_ms, params.heartbeat_ms, False,
                lat_deliver=ld, packed=params.packed_state,
            )
            if mesh is not None:
                t_rx, _, _ = converge_sharded(
                    t0, c, params.max_relax_iters, mesh, g_floor=g_floor)
            else:
                t_rx, _, _ = converge_recv(
                    t0, c, params.max_relax_iters, g_floor=g_floor)
            return t_rx
        queue = (rank + 1.0 + frag_idx * k_p[:, None]) * tx_ms[:, None]
        a_base = jnp.where(
            deliver & can_send[:, None], queue + ld, INF)

        def cond(carry):
            _, changed, it = carry
            return changed & (it < params.max_relax_iters)

        def body(carry):
            t_rx, _, it = carry
            live = (t_rx < INF)[:, None]
            start = jnp.maximum(t_rx + params.proc_delay_ms, uplink)
            cand = jnp.where(live, start[:, None] + a_base, INF)
            t_new = jnp.minimum(
                t_rx,
                jnp.maximum(
                    jnp.minimum(pull(cand).min(axis=-1), g_floor), rx_const))
            return t_new, jnp.any(t_new < t_rx), it + 1

        t_rx, _, _ = jax.lax.while_loop(
            cond, body, (t0, jnp.bool_(True), jnp.int32(0)))
        return t_rx

    def _converge_serialized(rank, k_p, frag_idx, t_pub, send_mask,
                             t_seed=None):
        """Exact fixpoint of the SERIALIZED answer model, as an outer
        iteration on the gossip ESTIMATE: each pass freezes the serialized
        answer offers at the current estimate t_g, then re-relaxes the
        whole network FROM SCRATCH against that floor. The from-INF
        restart is load-bearing (r5 review catch): the serialized system
        is NOT monotone in t — raising an announcer's estimate delays its
        IHAVE, which can REMOVE a requested job and make other answers
        earlier — so a warm-started min-only relaxation could undershoot
        and stick. A from-INF pass instead always lands exactly on
        min(candidates | frozen g), so when a pass reproduces its own
        estimate (t_new == t_g) the result is SELF-CONSISTENT:
        t = min(candidates(t)) with every gossip term evaluated at t.
        Any self-consistent point equals the DES's chronological fixpoint
        — a hypothetically-early solution would need its earliest wrong
        peer's candidate to be justified by strictly-earlier inputs, which
        are all correct by minimality, reproducing the true (later) value;
        contradiction. `t_seed`: optional starting estimate for the gossip
        terms (e.g. the phase-1 result), purely a convergence accelerator.

        Returns (t, converged, passes): `converged` is the final no-change
        bit of the outer loop — False means the iteration cap cut the
        refinement and t is NOT certified self-consistent (the caller
        surfaces this on DisseminationResult.converged instead of silently
        reporting a 0.0 error bar); `passes` the outer passes spent
        (DisseminationResult.refine_passes)."""
        sv = _frag_slice(survive, frag_idx)

        def cond(carry):
            _, _, changed, it = carry
            return changed & (it < params.max_relax_iters)

        def body(carry):
            t_g, _, _, it = carry
            g_abs, _, _ = gossip_serial_exact(t_g, frag_idx)
            g_d = g_abs if sv is None else jnp.where(sv, g_abs, INF)
            g_in = reciprocal_pull_min(
                g_d, conns, rev, batch_factor=fragments)
            g_floor = g_in.min(axis=-1)
            t_new = _converge_floor(
                rank, k_p, frag_idx, t_pub, send_mask, g_floor,
                jnp.full((n,), INF))
            return t_new, t_new, jnp.any(t_new != t_g), it + 1

        t0 = (jnp.full((n,), INF) if t_seed is None else t_seed
              ).at[publisher].set(t_pub)
        _, t, changed, it = jax.lax.while_loop(
            cond, body, (t0, t0, jnp.bool_(True), jnp.int32(0)))
        return t, ~changed, it

    def _converge_prefix(rank, k_p, frag_idx, t_pub, send_mask, t_seed):
        """Exact fixpoint of the SERIALIZED answer model by scan-free
        Jacobi iteration — the parallel-prefix replacement for the
        _converge_serialized outer loop. One iteration evaluates the full
        candidate map F at the current estimate and takes it wholesale:
        the lat-sorted answer-queue fold (gossip_fold — itself a
        parallel-prefix cumsum/cummax over the static service order, no
        global argsort) gives every edge's serialized answer offer, the
        hoisted mesh bases give the uplink-queue offers, and ONE merged
        pull yields t_{k+1} = max(min incoming offer, downlink clamp) with
        the publisher pinned. Because each estimate is recomputed FRESH
        (not min-folded into the previous one), the iteration handles the
        system's non-monotonicity in both directions — raising an
        announcer's estimate delays its IHAVE and may REMOVE a requested
        job, making other answers earlier — where a warm min-only
        relaxation would undershoot and stick (the r5 review catch that
        forced _converge_serialized's from-INF restarts).

        The exactness certificate is unchanged: the loop exits on a
        bitwise no-change pass, i.e. F(t) == t — the result is
        SELF-CONSISTENT (t = min(candidates(t)) with every gossip term
        evaluated at t), and any self-consistent point equals the DES's
        chronological fixpoint by the earliest-wrong-peer argument in
        _converge_serialized's docstring. What changes is the per-pass
        price: one fold + one pull, vs the serial path's global (N, H*C)
        argsort + a full from-INF mesh relaxation (~graph-diameter pulls)
        per outer pass.

        Returns (t, g_abs, req, drain, mixed, converged, passes) — the
        gossip triple and `mixed` are the FINAL evaluation's (the
        no-change pass ran the fold at the fixpoint, so they ride out for
        free); `mixed` or ~converged sends the caller to the global-sort
        fallback, whose round-interleaving-proof sort covers the corner
        the per-round fold cannot certify."""
        sv = _frag_slice(survive, frag_idx)
        ld = _ld_mesh(frag_idx)
        deliver = send_mask if sv is None else send_mask & sv
        queue = (rank + 1.0 + frag_idx * k_p[:, None]) * tx_ms[:, None]
        a_base = jnp.where(
            deliver & can_send[:, None], queue + ld, INF)
        t0 = t_seed.at[publisher].set(t_pub)
        not_pub = jnp.arange(n) != publisher

        def cond(carry):
            changed, it = carry[-2], carry[-1]
            return changed & (it < params.max_relax_iters)

        def body(carry):
            t_g, _, _, _, _, _, it = carry
            g_abs, req, drain, mixed, _ = gossip_fold(t_g, frag_idx)
            # merged candidates: mesh offers + SV-masked serialized answer
            # offers (every sampled surviving edge offers, matching the
            # serial path — an offer only binds for a still-lacking, hence
            # requesting, receiver)
            g_d = g_abs if sv is None else jnp.where(sv, g_abs, INF)
            live = (t_g < INF)[:, None]
            start = jnp.maximum(t_g + params.proc_delay_ms, uplink)
            cand = jnp.where(live, start[:, None] + a_base, INF)
            cand = jnp.minimum(cand, jnp.where(live, g_d, INF))
            inc = pull(cand)
            t_new = jnp.where(
                not_pub,
                jnp.maximum(inc.min(axis=-1), rx_const), t_pub)
            return (t_new, g_abs, req, drain, mixed,
                    jnp.any(t_new != t_g), it + 1)

        t, g_abs, req, drain, mixed, changed, it = jax.lax.while_loop(
            cond, body,
            (t0, jnp.full((n, c), INF), jnp.zeros((n, c), bool),
             jnp.zeros((n,), jnp.float32), jnp.bool_(False),
             jnp.bool_(True), jnp.int32(0)))
        return t, g_abs, req, drain, mixed, ~changed, it

    def queue_drop(tgt_mask, frag_idx):
        """Priority-queue drop model (main.nim:264-299). The reference's
        queues are per-CONNECTION and hold MESSAGES: the publisher enqueues
        all fragments back-to-back on every connection (main.nim:177-179),
        so its per-connection depth for fragment f is f+1 and the newest
        fragments beyond the cap are dropped — identically on every
        connection, so a publisher cap < FRAGMENTS blacks the message out
        network-wide (nobody can assemble it), which is what the reference
        does too. Relay inter-fragment arrival gaps are >= one link latency
        (tens of ms >> tx), so relay queues drain between fragments and
        never overflow. Statically a no-op when the cap cannot bind."""
        if params.send_queue_cap >= fragments:
            return tgt_mask
        is_pub = (jnp.arange(n) == publisher)[:, None]
        dropped = frag_idx + 1.0 > params.send_queue_cap
        return tgt_mask & ~(is_pub & dropped)

    def _phase2_masks_from_inc(inc1, t1, rank1, k1, tgt_f):
        """Back-edge removal: drop each peer's slot toward its first sender
        from the send order — the slot is simply never occupied. The first
        sender is whoever DELIVERED: `inc1` is the pulled deliver-only
        offer matrix at t1 (lost copies masked; gossip offers only on
        ANSWERED edges — an unanswered edge's hypothetical offer never
        binds and must not steal the attribution argmin)."""
        first_slot = jnp.argmin(inc1, axis=-1)
        # the min offer equals t1 BY CONSTRUCTION at the fixpoint (every
        # reached non-publisher peer's time IS some offer), but offers() and
        # the converge body associate the same sum differently in f32, so the
        # equality needs a tolerance or a 1-ulp wobble leaves a receiver's
        # back-edge in place (caught by the DES cross-check). The relative
        # term keeps the tolerance above the f32 ulp at large sim times; a
        # generous value is safe — the only peers whose min offer truly
        # exceeds t1 are unreached ones (INF on both sides)
        # (t1 < INF) makes the reached-peer precondition explicit: for
        # unreached peers INF <= INF + eps is vacuously true and would strip
        # a phantom back-edge at slot 0
        got_remote = (inc1.min(axis=-1) <= t1 + 0.01 + 1e-5 * t1) \
            & (t1 < INF) & (jnp.arange(n) != publisher)
        # row-wise one-hot via fused iota compare (scatters serialize on TPU)
        back = (jnp.arange(c) == first_slot[:, None]) & got_remote[:, None]
        send_mask = tgt_f & ~back
        # re-rank WITHOUT re-sorting: at most one slot left each row's send
        # order (the back-edge, IF it was a send target at all — a first
        # sender needn't be one of ours), so ranks after the removed slot's
        # rank shift down by one; rows with no active removal shift nothing
        # (r0 is +INF there). Replaces a double argsort with fused passes.
        rm = got_remote & jnp.take_along_axis(
            tgt_f, first_slot[:, None], axis=-1)[:, 0]
        r0 = jnp.where(rm,
                       jnp.take_along_axis(
                           rank1, first_slot[:, None], axis=-1)[:, 0], INF)
        rank2 = rank1 - (rank1 > r0[:, None])
        k2 = k1 - rm.astype(jnp.float32)
        return rank2, k2, send_mask

    def _diverged(t, inc, mixed):
        """Self-consistency trigger of the fast path (zero extra cost: it
        reuses the already-pulled serialized candidates). The unserialized
        fixpoint t satisfies t = min(unserialized candidates) <= the
        serialized min; if t also >= the serialized candidate min (within
        float tolerance), the two coincide and t IS the serialized
        fixpoint by uniqueness (a hypothetically-earlier self-consistent
        solution would need its earliest wrong peer justified by
        strictly-earlier — hence correct — inputs, contradiction). A peer
        strictly below every serialized candidate means a queued answer
        it relied on would really arrive later: rerun serialized. `mixed`
        (interleaved announce rounds, beyond the per-round fold) also
        forces the exact path."""
        inc_min = inc.min(axis=-1)
        tol = 0.05 + 1e-5 * jnp.where(t < INF, t, 0.0)
        bad = (t < inc_min - tol) & (t < INF) \
            & (jnp.arange(n) != publisher)
        return jnp.any(bad) | mixed

    def phases_fast(frag_idx, t_pub, warm):
        """UNSERIALIZED two-phase pipeline. Contains no lax.cond, so it is
        safe under the fragment vmap.

        EXACT mode (serialize_answers=True): the serialized answer queues
        are resolved at both phase results by the cheap per-round fold
        (gossip_fold): the queue delays ride in the attribution pulls and
        the accounting triple, while the delivery fixpoint stays
        unserialized. The _diverged triggers (checked at both phases)
        certify when that is exact — a queued answer only matters if it
        would have been somebody's FIRST delivery — and route the message
        to the serialized slow branch otherwise.

        BOUNDED mode (serialize_answers=False) and the no-gossip model:
        the fold's output never moves a delivery time — it only feeds the
        answer_wait_max_ms error bar and the accounting triple — so it has
        no business riding every phase (the r5 regression: two folds plus
        two attribution offers()+pull per fragment on the path whose whole
        point is speed). The first-sender attribution reuses the fixpoint
        loop's confirmation-pass offer matrix (free, bit-consistent with
        the times it attributes — see _converge_dyn), and ONE fold at the
        final times supplies the triple and the wait bar. The gossip
        entries of that matrix are the UNSERIALIZED offers, consistent
        with bounded delivery semantics; they deviate from the serialized
        values only when an answer queued, which is exactly what the
        exported wait bar brackets.

        `warm` (static): seed phase 1 from the cross-publish arrival-
        offset carry (state.warm_offset_ms), re-based to this publish via
        t_pub + offset[q] + offset[publisher] + one heartbeat of margin —
        the publisher term covers publishing from a peer that was LATE in
        the previous spread, the heartbeat margin covers gossip-round
        phase shifts. The seed is a HEURISTIC upper-bound estimate, so the
        result is certified: at a correct fixpoint every reached
        non-publisher peer satisfies t == max(min incoming offer, downlink
        clamp) BITWISE (the loop's no-change pass computed t from this
        very inc), while a stuck undershot seed sits strictly BELOW its
        supported value (min-only relaxation never raises it) — `bad`
        flags any such peer and the message level reruns cold on a scalar
        cond (a vmapped cond here would execute both branches every
        publish).

        Returns (t, rank, k, send_mask, g_abs, req_any, drain, inc, wait,
        hint, mixed, ok, bad) — `wait` is the fold's max answer-queue wait
        at the final times (always FINITE; `mixed` separately flags the
        interleaved-rounds corner where the fold's per-round exactness
        precondition fails), `ok` the fixpoint-convergence bit, `bad` the
        warm-seed certificate violation."""
        tgt_f = queue_drop(tgt, frag_idx)
        rank1 = _ranks_f32(jnp.where(tgt_f, rprio, INF))
        k1 = tgt_f.sum(axis=-1).astype(jnp.float32)
        if warm:
            w = state.warm_offset_ms
            seed = jnp.where(
                (w < WARM_VALID) & (w[publisher] < WARM_VALID),
                t_pub + w + w[publisher] + params.heartbeat_ms, INF)
            t1, inc1, ok1 = _converge_dyn(rank1, k1, frag_idx, t_pub,
                                          tgt_f, t_init=seed)
            supported = jnp.maximum(inc1.min(axis=-1), rx_const)
            # t1 <= supported holds at any loop exit; strict < means the
            # seed undershot and stuck (or a phantom: a finite seed on a
            # peer no offer reaches keeps supported at INF). An
            # iteration-capped run leaves inc one pass stale, so it cannot
            # certify either.
            bad = jnp.any((t1 < supported) & (t1 < INF) & ~is_pub) | ~ok1
        else:
            t1, inc1, ok1 = _converge_dyn(rank1, k1, frag_idx, t_pub, tgt_f)
            bad = jnp.bool_(False)
        if with_gossip and params.serialize_answers:
            g1, req1, drain1, mixed1, wait1 = gossip_fold(t1, frag_idx)
            ga1 = jnp.where(req1, g1, INF)
            if not params.exclude_first_sender:
                inc2 = pull(offers(t1, rank1, k1, frag_idx, tgt_f,
                                   deliver_only=True, g_abs=ga1))
                hint = _diverged(t1, inc2, mixed1)
                return (t1, rank1, k1, tgt_f, g1, req1, drain1, inc2,
                        wait1, hint, mixed1, ok1, bad)
            inc1p = pull(offers(t1, rank1, k1, frag_idx, tgt_f,
                                deliver_only=True, g_abs=ga1))
            rank2, k2, send_mask = _phase2_masks_from_inc(
                inc1p, t1, rank1, k1, tgt_f)
            # phase-2 costs are pointwise <= phase-1 (a send slot was
            # removed from every queue), so t1 is a valid warm start
            t2, _, ok2 = _converge_dyn(rank2, k2, frag_idx, t_pub,
                                       send_mask, t_init=t1)
            g2, req2, drain2, mixed2, wait2 = gossip_fold(t2, frag_idx)
            inc2 = pull(offers(t2, rank2, k2, frag_idx, send_mask,
                               deliver_only=True,
                               g_abs=jnp.where(req2, g2, INF)))
            hint = (_diverged(t1, inc1p, mixed1)
                    | _diverged(t2, inc2, mixed2))
            # error bar covers BOTH folds the fast result relied on (the
            # t1 fold fed the first-sender attribution)
            return (t2, rank2, k2, send_mask, g2, req2, drain2, inc2,
                    jnp.maximum(wait1, wait2), hint, mixed1 | mixed2,
                    ok1 & ok2, bad)
        # bounded / no-gossip: attribution from the loop's own matrix
        if not params.exclude_first_sender:
            t_fin, inc_fin, ok = t1, inc1, ok1
            rank_o, k_o, mask_o = rank1, k1, tgt_f
        else:
            rank2, k2, send_mask = _phase2_masks_from_inc(
                inc1, t1, rank1, k1, tgt_f)
            # t1 is a valid (guaranteed) upper bound for phase 2 — no
            # certificate needed
            t2, inc2, ok2 = _converge_dyn(rank2, k2, frag_idx, t_pub,
                                          send_mask, t_init=t1)
            t_fin, inc_fin, ok = t2, inc2, ok1 & ok2
            rank_o, k_o, mask_o = rank2, k2, send_mask
        if with_gossip:
            g_f, req_f, drain_f, mixed_o, wait_o = gossip_fold(
                t_fin, frag_idx)
        else:
            g_f = jnp.zeros((n, c), jnp.float32)
            req_f = jnp.zeros((n, c), bool)
            drain_f = jnp.zeros((n,), jnp.float32)
            mixed_o, wait_o = jnp.bool_(False), jnp.float32(0.0)
        return (t_fin, rank_o, k_o, mask_o, g_f, req_f, drain_f, inc_fin,
                wait_o, jnp.bool_(False), mixed_o, ok, bad)

    def phases_serial(frag_idx, t_pub, t_seed):
        """SERIALIZED pipeline: exact answer queues inside the delivery
        fixpoint itself (from-INF outer iteration) and in the accounting
        triple. Reached only from the trigger-gated slow branch (a
        scalar-predicate lax.cond at message level — a real XLA branch,
        never a batched select), so its global sorts and outer passes cost
        nothing unless a QUEUED answer was actually somebody's first
        delivery (or announce rounds interleaved). `t_seed`: the fast
        pipeline's final times — a near-correct gossip estimate that cuts
        the outer passes from reach-expansion count (~10) to tick/request
        refinement count (~2-3)."""
        tgt_f = queue_drop(tgt, frag_idx)
        rank1 = _ranks_f32(jnp.where(tgt_f, rprio, INF))
        k1 = tgt_f.sum(axis=-1).astype(jnp.float32)
        t1, conv1, it1 = _converge_serialized(rank1, k1, frag_idx, t_pub,
                                              tgt_f, t_seed=t_seed)
        if not params.exclude_first_sender:
            g2, req2, drain2 = gossip_serial_exact(t1, frag_idx)
            inc2 = pull(offers(t1, rank1, k1, frag_idx, tgt_f,
                               deliver_only=True,
                               g_abs=jnp.where(req2, g2, INF)))
            return t1, rank1, k1, tgt_f, g2, req2, drain2, inc2, conv1, it1
        g1, req1, _ = gossip_serial_exact(t1, frag_idx)
        inc1 = pull(offers(t1, rank1, k1, frag_idx, tgt_f,
                           deliver_only=True,
                           g_abs=jnp.where(req1, g1, INF)))
        rank2, k2, send_mask = _phase2_masks_from_inc(
            inc1, t1, rank1, k1, tgt_f)
        t2, conv2, it2 = _converge_serialized(rank2, k2, frag_idx, t_pub,
                                              send_mask, t_seed=t1)
        g2, req2, drain2 = gossip_serial_exact(t2, frag_idx)
        inc2 = pull(offers(t2, rank2, k2, frag_idx, send_mask,
                           deliver_only=True,
                           g_abs=jnp.where(req2, g2, INF)))
        return (t2, rank2, k2, send_mask, g2, req2, drain2, inc2,
                conv1 & conv2, it1 + it2)

    def phases_prefix(frag_idx, t_pub, t_seed):
        """PARALLEL-PREFIX serialized pipeline (the exact-mode default,
        params.answer_queue_mode="parallel_prefix"): the same two-phase
        structure as phases_serial with _converge_prefix supplying both
        fixpoints — exact answer queues inside the delivery times at one
        fold + one pull per refinement iteration, no global sorts, no
        from-INF restarts. Reached only from the trigger-gated slow
        branch; `t_seed` is the fast pipeline's final times, so the Jacobi
        iteration starts from a near-correct estimate and spends
        tick/request-refinement iterations, not reach-expansion ones.

        Returns the phases_serial 10-tuple with element 8 = the COMBINED
        certificate (both phases reached a bitwise F(t)==t pass AND
        neither's final fold saw interleaved announce rounds). A False
        certificate means the prefix times are NOT certified exact —
        the caller's nested cond reruns the global-sort pipeline, whose
        sort-order exactness covers the interleaved corner."""
        tgt_f = queue_drop(tgt, frag_idx)
        rank1 = _ranks_f32(jnp.where(tgt_f, rprio, INF))
        k1 = tgt_f.sum(axis=-1).astype(jnp.float32)
        t1, g1, req1, drain1, mixed1, conv1, it1 = _converge_prefix(
            rank1, k1, frag_idx, t_pub, tgt_f, t_seed)
        # attribution pull: gossip offers masked to ANSWERED edges — an
        # unanswered edge's hypothetical offer must not steal the
        # first-sender argmin (same masking as phases_serial)
        inc1 = pull(offers(t1, rank1, k1, frag_idx, tgt_f,
                           deliver_only=True,
                           g_abs=jnp.where(req1, g1, INF)))
        if not params.exclude_first_sender:
            return (t1, rank1, k1, tgt_f, g1, req1, drain1, inc1,
                    conv1 & ~mixed1, it1)
        rank2, k2, send_mask = _phase2_masks_from_inc(
            inc1, t1, rank1, k1, tgt_f)
        t2, g2, req2, drain2, mixed2, conv2, it2 = _converge_prefix(
            rank2, k2, frag_idx, t_pub, send_mask, t1)
        inc2 = pull(offers(t2, rank2, k2, frag_idx, send_mask,
                           deliver_only=True,
                           g_abs=jnp.where(req2, g2, INF)))
        return (t2, rank2, k2, send_mask, g2, req2, drain2, inc2,
                conv1 & conv2 & ~mixed1 & ~mixed2, it1 + it2)

    # publisher emits fragments back-to-back (main.nim:177-179)
    frag_ids = jnp.arange(fragments, dtype=jnp.float32)
    t_pubs = t0_ms + frag_ids * tx_ms[publisher]

    def _run_fast(warm):
        if mesh is None:
            return jax.vmap(
                lambda f, t: phases_fast(f, t, warm))(frag_ids, t_pubs)
        # shard_map doesn't nest under vmap; fragments is static and <= 9
        # (topogen -f choices), so unroll the fragment axis instead
        outs = [phases_fast(frag_ids[i], t_pubs[i], warm)
                for i in range(fragments)]
        return tuple(jnp.stack(x) for x in zip(*outs))

    fast = _run_fast(params.warm_start)
    if params.warm_start:
        # the warm seed is heuristic: if ANY fragment's certificate flags
        # an undershoot (or a capped loop), restart the whole fast
        # pipeline cold. Scalar-predicate cond = a real XLA branch; never
        # taken when the seed margin holds, so the cold trace costs
        # compile time only.
        fast = jax.lax.cond(
            jnp.any(fast[12]), lambda _: _run_fast(False),
            lambda f: f, fast)
    (fast_results, wait_f, hint_f, mixed_f, ok_f) = (
        fast[:8], fast[8], fast[9], fast[10], fast[11])
    # bounded-mode error bar: the max time any requested answer waited
    # queued at the final estimates — in exact mode the repair (below)
    # drives the actual delivery error to zero and this reports 0.
    # ALWAYS finite (json-safe): the interleaved-rounds corner, where the
    # per-round fold's bar is unreliable, is exported as a separate COUNT
    # instead of the old INF poison (which leaked invalid-JSON Infinity
    # into bench artifacts).
    answer_wait = jnp.max(wait_f)
    answer_interleaved = jnp.sum(mixed_f.astype(jnp.int32))
    converged = jnp.all(ok_f)
    refine_passes = jnp.int32(0)
    if with_gossip and params.serialize_answers:
        # serialized-answer repair, decided ONCE per message on a SCALAR
        # predicate (_diverged): the fast pipeline is kept whenever no
        # queued answer could have been a first delivery and no announce
        # rounds interleaved — then the unserialized times are themselves
        # the serialized fixpoint and the triple/inc are already exact.
        # The scalar cond is a real branch on TPU — a vmapped cond would
        # lower to select_n and execute both branches every publish (the
        # r5 review + bench catch). The fast results ride in as the
        # operand: the slow pipeline seeds its gossip estimates from them.
        #
        # Engine selection (static): the parallel-prefix pipeline needs
        # the single-device row-gather pull its Jacobi body is built
        # around, so it runs exactly where _converge_dyn picks that
        # dispatch — mesh-free and under the memory budget (the nested
        # device grids call disseminate with mesh=None inside pjit, so
        # they ride it too). Elsewhere, and under answer_queue_mode=
        # "serial" (the reference engine the prefix path is pinned
        # against), the global-sort pipeline runs as before.
        use_prefix = (params.answer_queue_mode == "parallel_prefix"
                      and mesh is None
                      and not exceeds_budget(jnp.float32, conns.shape,
                                             fragments))

        def _serial_all(seed):
            outs = [phases_serial(frag_ids[i], t_pubs[i], seed[i])
                    for i in range(fragments)]
            return tuple(jnp.stack(x) for x in zip(*outs))

        def _slow(fr):
            t_fast = fr[0]
            if not use_prefix:
                return _serial_all(t_fast)
            outs = [phases_prefix(frag_ids[i], t_pubs[i], t_fast[i])
                    for i in range(fragments)]
            pref = tuple(jnp.stack(x) for x in zip(*outs))

            # certificate-gated fallback (nested scalar cond): any
            # fragment the prefix engine could not certify — interleaved
            # announce rounds or an iteration-capped Jacobi loop — reruns
            # ALL fragments through the global-sort pipeline, seeded from
            # the prefix times. Untaken, the legacy branch costs compile
            # time only (the repo's warm-rerun idiom); its pass count adds
            # to the prefix iterations already spent.
            def _legacy(p):
                leg = _serial_all(p[0])
                return leg[:9] + (p[9] + leg[9],)

            return jax.lax.cond(
                jnp.all(pref[8]), lambda p: p, _legacy, pref)

        # the convergence bit rides the cond operand so the kept branch's
        # verdict (fast ok / serialized refinement certificate) wins; the
        # pass counter rides alongside (0 when the fast pipeline is kept)
        fast10 = jax.lax.cond(
            jnp.any(hint_f), _slow, lambda fr: fr,
            fast_results + (ok_f, jnp.zeros((fragments,), jnp.int32)))
        fast_results, conv_f, passes_f = fast10[:8], fast10[8], fast10[9]
        converged = jnp.all(conv_f)
        refine_passes = jnp.max(passes_f)
        # exact mode: the repair drives the delivery error to zero
        answer_wait = jnp.float32(0.0)
        answer_interleaved = jnp.int32(0)
    (t_rx_f, rank_f, k_f, smask_f, g_abs_acct, req_acct,
     drain_acct, inc_acct) = fast_results

    received = jnp.all(t_rx_f < INF, axis=0)
    t_rx = jnp.where(received, t_rx_f.max(axis=0), INF)  # last fragment completes
    delay = jnp.where(received, t_rx - t0_ms, INF)


    # ---- post-fixpoint accounting (bytes, duplicates, gossip, score) -------
    def frag_accounting(frag_idx, t_rx_one, rank, k_p, send_mask,
                        g_abs_f, req_any_f, drain_f, inc):
        # this fragment's loss draw; the gossip triple (answer offers,
        # answered sets, serialized queue drain) and the deliver-only
        # offer matrix `inc` were resolved at the final times by the phase
        # pipeline (fold or exact per the trigger branch; in bounded mode
        # `inc` is the fixpoint loop's own confirmation-pass matrix, whose
        # gossip entries are the unserialized offers — the deviation from
        # the serialized values is bracketed by answer_wait_max_ms)
        sv = _frag_slice(survive, frag_idx)
        # loss-only draw (pre-graylist) for the lost_tx counter: a
        # receiver-side graylist ignore is not a network loss
        sv_loss = _frag_slice(survive_loss, frag_idx)
        if not with_gossip:
            g_abs_f = None
        # tx side (sends, bytes): everything transmitted, lost or not
        cand = offers(t_rx_one, rank, k_p, frag_idx, send_mask,
                      g_abs=g_abs_f)
        made_offer = cand < INF
        # rx side (first-delivery attribution): delivered copies only
        first_slot = jnp.argmin(inc, axis=-1)
        q_t = neighbor_pull_min(  # neighbor arrival times (fragment-vmapped)
            t_rx_one, conns, rev, batch_factor=fragments)
        start_tx = jnp.maximum(t_rx_one + params.proc_delay_ms, uplink)
        # IDONTWANT (v1.2): target announced receipt before our send began
        if payload_bytes >= params.idontwant_threshold_bytes:
            send_start = start_tx[:, None] \
                + (rank + frag_idx * k_p[:, None]) * tx_ms[:, None]
            idw_arrived = q_t + lat_edge < send_start
            made_offer = made_offer & ~(idw_arrived & send_mask)
        eff_send = made_offer & send_mask
        sends = eff_send.sum(axis=-1)
        # uplink occupancy of this fragment's mesh sends: the queue drains at
        # the end of the LAST slot actually transmitted. Slot positions stay
        # fixed when an IDONTWANT suppresses an earlier send (the delivery
        # model keeps static ranks), so only trailing suppressed slots
        # shorten the drain.
        last_pos = jnp.max(jnp.where(eff_send, rank + 1.0, 0.0), axis=-1)
        up_end = jnp.where(
            last_pos > 0.0,
            start_tx + (frag_idx * k_p + last_pos) * tx_ms, 0.0)
        if with_gossip:
            havers = (t_rx_one < INF) & can_send
            # per-round accounting over the mcache window: every heartbeat
            # tick h the emitter IHAVEs its fresh sample; the receiver
            # IWANTs only if it still lacks the message when the announce
            # lands — the phase pipeline already resolved the answered sets
            # (req_any_f) and the serialized drain of each peer's answer
            # queue (drain_f: announce tick, IWANT round trip, then the
            # answers transmitted BACK-TO-BACK on the answering uplink in
            # IWANT-arrival order — sum, not max; rounds chain through the
            # running busy time). The DES recomputes both through its
            # chronological event heap.
            ihave_ct = jnp.zeros((n, c), jnp.float32)   # per-edge IHAVEs
            for h in range(n_rounds):
                ihave_ct = ihave_ct + (g_tgt_w[h] & havers[:, None])
            gossip_sent = req_any_f                     # edge answered >=1 IWANT
            up_end = jnp.maximum(up_end, drain_f)
            ihave_pp = ihave_ct.sum(axis=-1)            # (N,) IHAVEs sent
            # the IWANT flows opposite the IHAVE: the lacking RECEIVER sends
            # it, the gossiping peer receives it
            iwant_rx_pp = gossip_sent.sum(axis=-1).astype(jnp.float32)
            sends = sends + (gossip_sent & made_offer).sum(axis=-1)
            sent_any = eff_send | (gossip_sent & made_offer)
            arrived = sent_any if sv is None else sent_any & sv
            lost_pp = (jnp.zeros((n,), jnp.float32) if sv_loss is None
                       else (sent_any & ~sv_loss).sum(axis=-1)
                       .astype(jnp.float32))
            # ONE pull for all three involution-crossing quantities: the
            # per-edge IHAVE count (<= history_gossip), the IWANT flag and
            # the delivered-copy flag pack exactly into one small float —
            # every extra pull is a full row-gather pass (ops/pull.py), so
            # 3 -> 1 saves two passes per fragment
            pack = (ihave_ct * 4.0 + gossip_sent.astype(jnp.float32) * 2.0
                    + arrived.astype(jnp.float32))
            slot_ok = (conns >= 0) & (rev >= 0)
            pulled = jnp.where(
                slot_ok,
                reciprocal_pull_min(pack, conns, rev, batch_factor=fragments),
                0.0)
            q_ihave = jnp.floor(pulled / 4.0)
            rem = pulled - q_ihave * 4.0
            q_gs = jnp.floor(rem / 2.0)
            ihave_rx_pp = q_ihave.sum(axis=-1)
            iwant_pp = q_gs.sum(axis=-1)
            arrived_rx = rem - q_gs * 2.0 > 0.5         # (N, C) copy landed
            copies = arrived_rx.sum(axis=-1).astype(jnp.float32)
        else:
            ihave_pp = jnp.zeros((n,), jnp.float32)
            iwant_pp = jnp.zeros((n,), jnp.float32)
            ihave_rx_pp = jnp.zeros((n,), jnp.float32)
            iwant_rx_pp = jnp.zeros((n,), jnp.float32)
            sent_any = eff_send
            # receivers only count copies the network actually delivered
            arrived = sent_any if sv is None else sent_any & sv
            lost_pp = (jnp.zeros((n,), jnp.float32) if sv_loss is None
                       else (sent_any & ~sv_loss).sum(axis=-1)
                       .astype(jnp.float32))
            arrived_rx = reciprocal_pull_bool(
                arrived, conns, rev, batch_factor=fragments)
            copies = arrived_rx.sum(axis=-1).astype(jnp.float32)
        # wire-arrival time of every copy that landed at each receiver slot
        # (for the downlink-occupancy fold below); -INF marks no-copy slots
        arr_t = jnp.where(arrived_rx, inc, -INF)
        # slow-peer penalty (main.nim:264-299): deliveries that spent longer
        # than the threshold in the SENDER's queue mark the sender as slow
        # in the RECEIVER's score of it (the reciprocal slot) — scoring and
        # opportunistic grafting then route around low-bandwidth peers.
        # Weight 0 (the default) statically removes the computation.
        if params.slow_weight != 0.0:
            # queue delay as the receiver experiences it: the wait for the
            # sender's uplink to drain earlier traffic counts too
            qdelay = jnp.maximum(
                uplink - (t_rx_one + params.proc_delay_ms), 0.0
            )[:, None] + (rank + frag_idx * k_p[:, None]) * tx_ms[:, None]
            slow_send = send_mask & made_offer & (
                qdelay > params.slow_threshold_ms)
            slow_inc = reciprocal_pull_bool(
                slow_send, conns, rev, batch_factor=fragments
            ).astype(jnp.float32)
        else:
            slow_inc = jnp.zeros((n, c), jnp.float32)
        return (sends, copies, ihave_pp, iwant_pp, ihave_rx_pp, iwant_rx_pp,
                first_slot, slow_inc, arr_t, up_end, lost_pp)

    (sends_f, copies_f, ihave_f, iwant_f, ihave_rx_f, iwant_rx_f,
     first_slot_f, slow_f, arr_f, up_end_f, lost_f) = jax.vmap(
        frag_accounting
    )(frag_ids, t_rx_f, rank_f, k_f, smask_f, g_abs_acct, req_acct,
      drain_acct, inc_acct)
    sends = sends_f.sum(axis=0).astype(jnp.int32)
    lost_tx = lost_f.sum(axis=0).astype(jnp.int32)
    copies = copies_f.sum(axis=0).astype(jnp.int32)
    ihave_pp = ihave_f.sum(axis=0).astype(jnp.int32)
    iwant_pp = iwant_f.sum(axis=0).astype(jnp.int32)
    ihave_rx_pp = ihave_rx_f.sum(axis=0).astype(jnp.int32)
    iwant_rx_pp = iwant_rx_f.sum(axis=0).astype(jnp.int32)

    # firstMessageDeliveries: credit the edge that delivered fragment 0 first
    fs = first_slot_f[0]
    got = received & (jnp.arange(n) != publisher)
    # one credit at each receiver's first-delivery slot: a row-wise one-hot
    # add (fused elementwise) — scatters serialize on TPU
    credit = (jnp.arange(c) == fs[:, None]) & got[:, None]
    fmd = jnp.minimum(state.fmd + credit.astype(jnp.float32), params.fmd_cap)

    # IDONTWANT control-message counters (v1.2, go-test-node/main.go:165):
    # on first RECEIPT of a large message a peer announces IDONTWANT to its
    # mesh members except the one that delivered it — once per MESSAGE, not
    # per fragment; the publisher announces nothing (it received nothing).
    # The suppression effect rides inside frag_accounting; this is the
    # announce traffic. `credit` is exactly the first-delivery back-edge.
    if payload_bytes >= params.idontwant_threshold_bytes:
        idw_edge = (state.mesh_mask & valid & ~credit
                    & (got & can_send)[:, None])
        idw_tx_pp = idw_edge.sum(axis=-1).astype(jnp.int32)
        idw_rx_pp = reciprocal_pull_bool(
            idw_edge, conns, rev).sum(axis=-1).astype(jnp.int32)
    else:
        idw_tx_pp = jnp.zeros((n,), jnp.int32)
        idw_rx_pp = jnp.zeros((n,), jnp.int32)

    result = DisseminationResult(
        t_rx_ms=t_rx,
        delay_ms=delay,
        received=received,
        sends=sends,
        copies_rx=copies,
        ihave_sent=ihave_pp,
        iwant_sent=iwant_pp,
        lost_tx=lost_tx,
        answer_wait_max_ms=answer_wait,
        answer_interleaved=answer_interleaved,
        converged=converged,
        refine_passes=refine_passes,
    )
    dup = jnp.maximum(copies - fragments, 0)
    # uplink occupancy write-back: per fragment, frag_accounting computed the
    # effective drain end — the last mesh slot actually transmitted (IDONTWANT
    # suppression shortens trailing slots) plus answered-IWANT serializations.
    # Carried in SimState so the NEXT message's sends queue behind this one.
    uplink_new = jnp.maximum(uplink, up_end_f.max(axis=0))
    # downlink occupancy write-back: fold ALL delivered copies (mesh
    # duplicates + gossip answers, post-suppression) through each receiver's
    # single-server downlink queue in arrival order. For ascending arrivals
    # o_1..o_m the completion recurrence busy_j = max(o_j, busy_{j-1} + rx)
    # unrolls to busy_m = max(rx_free + m*rx, max_j o_j + (m-j)*rx); with d_i
    # the i-th LARGEST arrival that is max(rx_free + m*rx, max_i d_i + i*rx)
    # — one sort plus elementwise, order-exact (tied arrivals commute).
    arr_all = jnp.moveaxis(arr_f, 0, 1).reshape(n, fragments * c)
    d_sorted = -jnp.sort(-arr_all, axis=-1)
    m_copies = copies.astype(jnp.float32)
    pos = jnp.arange(fragments * c, dtype=jnp.float32)
    fold = jnp.where(pos[None, :] < m_copies[:, None],
                     d_sorted + pos[None, :] * rx_ms[:, None], -INF)
    rx_free_new = jnp.maximum(state.rx_free_ms + m_copies * rx_ms,
                              fold.max(axis=-1))
    # the counter accrues unweighted; score() applies the (negative) weight
    slow_penalty = state.slow_penalty + slow_f.sum(axis=0)
    # cross-publish warm-start carry: this message's arrival OFFSETS seed
    # the next publish's relaxation (phases_fast re-bases them to the new
    # publish time). INF where the message never fully arrived; churn and
    # subscription changes invalidate the carry (heartbeat/simulator).
    warm_new = jnp.where(received, t_rx - t0_ms, INF)
    new_state = state.replace(
        key=key,
        warm_offset_ms=warm_new,
        uplink_free_ms=uplink_new,
        rx_free_ms=rx_free_new,
        fmd=fmd,
        slow_penalty=slow_penalty,
        bytes_tx=state.bytes_tx + sends.astype(jnp.float32) * frag_bytes,
        bytes_rx=state.bytes_rx + copies.astype(jnp.float32) * frag_bytes,
        dup_rx=state.dup_rx + dup.astype(jnp.int32),
        ihave_tx=state.ihave_tx + ihave_pp,
        iwant_tx=state.iwant_tx + iwant_pp,
        ihave_rx=state.ihave_rx + ihave_rx_pp,
        iwant_rx=state.iwant_rx + iwant_rx_pp,
        idontwant_tx=state.idontwant_tx + idw_tx_pp,
        idontwant_rx=state.idontwant_rx + idw_rx_pp,
    )
    if with_fanout:
        # persist the publisher's (possibly replenished) fanout set and
        # restart its TTL from this publish
        new_state = new_state.replace(
            fanout_mask=jnp.where(is_pub[:, None], fan_row, state.fanout_mask),
            fanout_expire=jnp.where(
                is_pub,
                jnp.asarray(t0_ms + params.fanout_ttl_ms, jnp.float32),
                state.fanout_expire,
            ),
        )
    if return_plan:
        plan = {
            "tgt": tgt,                 # (N, C) data send set (pre queue-drop)
            "rprio": rprio,             # (N, C) send-order priorities
            "g_tgt_w": g_tgt_w,         # (W, N, C) per-round gossip targets
            "survive": survive,         # (F, N, C) per-fragment loss draws,
            #                             (N, C) graylist-only, or None
            "retx_ms": retx_ms,         # (F, N, C) tcp-mode retransmit
            #                             stall per delivered copy, or None
            "hb_phase": hb_phase,       # (N,)
            "uplink": uplink,           # (N,) pre-message uplink occupancy
            "rx_free": state.rx_free_ms,  # (N,) pre-message downlink occupancy
            "rx_ms": rx_ms,             # (N,) per-copy downlink drain time
            "can_send": can_send,       # (N,)
            "tx_ms": tx_ms,             # (N,) per-fragment uplink ms
            "lat_edge": lat_edge,       # (N, C) per-slot latency
            "t_pubs": t_pubs,           # (F,) per-fragment publish times
        }
        return result, new_state, plan
    return result, new_state


# ---------------------------------------------------------------------------
# Fused mega-round scan (ISSUE 16, ARCHITECTURE §18): the whole
# [heartbeat burst -> publish] round chain as ONE lax.scan over rounds.
# ---------------------------------------------------------------------------

def _fused_rounds_impl(state, ctrl, conns, rev, stage, lat_ms, bw, out_mask,
                       publishers, loss_stage, lat_edge, loss_edge,
                       ans_tables, valid_edge, censor_edge, attacker, crash,
                       side, spike, params, payload_bytes, hb_per_round,
                       fragments, with_gossip, loss_mode, batch_factor,
                       adv, faults, telemetry):
    # lazy imports: adversary/faults/telemetry all import heartbeat, which
    # must not import disseminate back at module level (publisher.py
    # precedent for breaking the cycle at the jit boundary)
    from .heartbeat import _run_heartbeats

    faulted = faults is not None and faults.enabled
    attacked = attacker is not None and adv is not None
    adaptive = attacked and adv.adaptive.enabled

    def hb(s, c):
        # Python-static composition switch: each branch calls the SAME
        # inner runner the phase-split chain jits, so the per-round trace
        # (hoists, carried degree, per-call deferred-decay materialization,
        # PRNG splits) is the phase-split program inlined under the scan.
        if faulted:
            from .faults import _run_faulted_heartbeats

            out, obs = _run_faulted_heartbeats(
                s, conns, rev, out_mask, attacker, crash, side, spike,
                params, adv, faults, hb_per_round, batch_factor, telemetry,
                c)
            return (out if adaptive else (out, c)) + (obs,)
        if adaptive:
            from .adversary import _run_adaptive_heartbeats

            (s, c), obs = _run_adaptive_heartbeats(
                s, c, conns, rev, out_mask, attacker, params, adv,
                hb_per_round, batch_factor, telemetry)
            return s, c, obs
        if attacked:
            from .adversary import _run_attacked_heartbeats

            s, obs = _run_attacked_heartbeats(
                s, conns, rev, out_mask, attacker, params, adv,
                hb_per_round, batch_factor, telemetry)
            return s, c, obs
        if telemetry is not None:
            from .telemetry import _run_recorded_heartbeats

            s, obs = _run_recorded_heartbeats(
                s, conns, rev, out_mask, params, telemetry, hb_per_round,
                batch_factor)
            return s, c, obs
        return _run_heartbeats(
            s, conns, rev, out_mask, params, hb_per_round), c, {}

    def body(carry, pub):
        s, c = carry
        s, c, obs = hb(s, c)
        res, s = disseminate(
            s, conns, rev, stage, lat_ms, bw, publisher=pub, t0_ms=s.t_ms,
            params=params, payload_bytes=payload_bytes, fragments=fragments,
            with_gossip=with_gossip, loss_stage=loss_stage,
            loss_mode=loss_mode, lat_edge=lat_edge, loss_edge=loss_edge,
            ans_tables=ans_tables, valid_edge=valid_edge,
            censor_edge=censor_edge)
        return (s, c), (res, obs)

    (state, ctrl), (results, obs) = jax.lax.scan(body, (state, ctrl),
                                                 publishers)
    return state, ctrl, results, obs


_fused_rounds_jit = None


def run_fused_rounds(state, conns, rev, stage, lat_ms, bw, out_mask,
                     publishers, params, payload_bytes, hb_per_round,
                     *, fragments=1, with_gossip=True, loss_stage=None,
                     loss_mode="tcp", lat_edge=None, loss_edge=None,
                     ans_tables=None, valid_edge=None, censor_edge=None,
                     attacker=None, adv=None, ctrl=None, faults=None,
                     crash=None, side=None, spike=None, telemetry=None,
                     batch_factor=1):
    """Run R = len(publishers) simulation rounds, each `hb_per_round`
    heartbeats followed by one publish from `publishers[r]` at the carried
    sim clock (t0_ms = state.t_ms, the bench chain's convention).

    `params.fused_rounds=False` (the default) literally delegates: a host
    loop over the SAME public per-phase entrypoints (run_heartbeats /
    run_attacked_heartbeats / run_adaptive_heartbeats /
    run_faulted_heartbeats / run_recorded_heartbeats, then disseminate)
    with the same statics — same jit cache entries, zero retraces on a
    warm call, zero extra PRNG splits, bit-identical outputs
    (tests/test_fused_rounds.py pins all four).

    `params.fused_rounds=True` fuses the whole chain into one lax.scan
    over rounds — one device dispatch for the entire R-round run instead
    of R x (phases) dispatches — by inlining the identical inner runners
    under a single trace. Delivery outcomes (received / lost_tx /
    answer_interleaved) stay bitwise equal to the phase-split chain; float
    delay fields carry an rtol because XLA may re-fuse arithmetic inside
    the scan body. Composition mirrors the delegating runners: a static
    attacker rides via (attacker, adv), the adaptive controller widens the
    carry via ctrl (defaulting to a fresh init_adaptive_ctrl), fault
    cohorts via (faults, crash, side, spike), and armed telemetry joins
    the per-round observables. Repair-inert params strip the 5 repair
    leaves around the whole fused program, exactly like every runner.

    Returns (state, results, obs) — results is a DisseminationResult whose
    leaves are stacked (R, ...), obs maps observable channels to
    (R, hb_per_round, ...) curves ({} when nothing is armed). With an
    armed adv.adaptive the first element widens to (state, ctrl)."""
    from .state import init_adaptive_ctrl, repair_inert, strip_repair

    faulted = faults is not None and faults.enabled
    attacked = attacker is not None and adv is not None
    adaptive = attacked and adv.adaptive.enabled
    if telemetry is not None and not telemetry.enabled:
        telemetry = None
    if (adv is None) != (attacker is None):
        raise ValueError("attacker and adv arm together — pass both or "
                         "neither")
    if faulted and not attacked:
        raise ValueError("faults compose on the attack window — pass "
                         "attacker and adv (a zero-attacker cohort is fine)")
    if ctrl is not None and not adaptive:
        raise ValueError("ctrl given but adv.adaptive is disabled — the "
                         "base runners carry none")
    if adaptive and ctrl is None:
        ctrl = init_adaptive_ctrl(params.n)

    if not params.fused_rounds:
        return _phase_split_rounds(
            state, conns, rev, stage, lat_ms, bw, out_mask, publishers,
            params, payload_bytes, hb_per_round, fragments, with_gossip,
            loss_stage, loss_mode, lat_edge, loss_edge, ans_tables,
            valid_edge, censor_edge, attacker, adv, ctrl, faults, crash,
            side, spike, telemetry, batch_factor, adaptive, faulted,
            attacked)

    global _fused_rounds_jit
    if _fused_rounds_jit is None:
        _fused_rounds_jit = jax.jit(
            _fused_rounds_impl,
            static_argnames=("params", "payload_bytes", "hb_per_round",
                             "fragments", "with_gossip", "loss_mode",
                             "batch_factor", "adv", "faults", "telemetry"))
    publishers = jnp.asarray(publishers, jnp.int32)
    saved = None
    if repair_inert(params):
        # disseminate neither reads nor writes the repair leaves, so the
        # heartbeat runners' host-side excision extends over the whole
        # fused program
        state, saved = strip_repair(state)
    state, ctrl, results, obs = _fused_rounds_jit(
        state, ctrl, conns, rev, stage, lat_ms, bw, out_mask, publishers,
        loss_stage, lat_edge, loss_edge, ans_tables, valid_edge,
        censor_edge, attacker, crash, side, spike, params, payload_bytes,
        hb_per_round, fragments, with_gossip, loss_mode, batch_factor, adv,
        faults, telemetry)
    if saved is not None:
        from .state import restore_repair

        state = restore_repair(state, saved)
    head = (state, ctrl) if adaptive else state
    return head, results, obs


def _phase_split_rounds(state, conns, rev, stage, lat_ms, bw, out_mask,
                        publishers, params, payload_bytes, hb_per_round,
                        fragments, with_gossip, loss_stage, loss_mode,
                        lat_edge, loss_edge, ans_tables, valid_edge,
                        censor_edge, attacker, adv, ctrl, faults, crash,
                        side, spike, telemetry, batch_factor, adaptive,
                        faulted, attacked):
    """The pinned phase-split reference: per round, the public delegating
    runner then disseminate — the literal pre-fusion program, dispatch for
    dispatch, cache entry for cache entry."""
    import numpy as np

    # jit cache keys include the call signature: passing a kwarg explicitly
    # at its default value is a DIFFERENT entry from omitting it, so only
    # non-default options ride into the disseminate call — the bench/
    # simulator chains' exact convention, which is what "same cache entry"
    # must mean for the disabled path
    dis_kw = {}
    if fragments != 1:
        dis_kw["fragments"] = fragments
    if not with_gossip:
        dis_kw["with_gossip"] = with_gossip
    if loss_stage is not None:
        dis_kw["loss_stage"] = loss_stage
    if loss_mode != "tcp":
        dis_kw["loss_mode"] = loss_mode
    if lat_edge is not None:
        dis_kw["lat_edge"] = lat_edge
    if loss_edge is not None:
        dis_kw["loss_edge"] = loss_edge
    if ans_tables is not None:
        dis_kw["ans_tables"] = ans_tables
    if valid_edge is not None:
        dis_kw["valid_edge"] = valid_edge
    if censor_edge is not None:
        dis_kw["censor_edge"] = censor_edge

    results = []
    obs_list = []
    for pub in np.asarray(publishers, dtype=np.int32).tolist():
        if faulted:
            from .faults import run_faulted_heartbeats

            out, obs = run_faulted_heartbeats(
                state, conns, rev, out_mask, attacker, params, adv, faults,
                crash, side, spike, hb_per_round, batch_factor, telemetry,
                ctrl)
            state, ctrl = out if adaptive else (out, ctrl)
        elif adaptive:
            from .adversary import run_adaptive_heartbeats

            (state, ctrl), obs = run_adaptive_heartbeats(
                state, conns, rev, out_mask, attacker, params, adv,
                hb_per_round, ctrl=ctrl, batch_factor=batch_factor,
                telemetry=telemetry)
        elif attacked:
            from .adversary import run_attacked_heartbeats

            state, obs = run_attacked_heartbeats(
                state, conns, rev, out_mask, attacker, params, adv,
                hb_per_round, batch_factor, telemetry)
        elif telemetry is not None:
            from .telemetry import run_recorded_heartbeats

            state, obs = run_recorded_heartbeats(
                state, conns, rev, out_mask, params, hb_per_round,
                telemetry, batch_factor)
        else:
            from .heartbeat import run_heartbeats

            state = run_heartbeats(state, conns, rev, out_mask, params,
                                   hb_per_round)
            obs = {}
        res, state = disseminate(
            state, conns, rev, stage, lat_ms, bw, publisher=pub,
            t0_ms=state.t_ms, params=params, payload_bytes=payload_bytes,
            **dis_kw)
        results.append(res)
        obs_list.append(obs)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *results)
    obs = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *obs_list)
    head = (state, ctrl) if adaptive else state
    return head, stacked, obs


