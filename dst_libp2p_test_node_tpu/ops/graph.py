"""Connection-graph substrate: the "dial phase" as array construction.

The reference forms its network by every peer shuffling [0..PEERS)\\{me} with a
per-process RNG and dialing the first CONNECTTO peers
(gossipsub-queues/main.nim:367-409; go-test-node/main.go:276-348;
rust-test-node/src/main.rs:303-345). Connections are symmetric and capped by
MAXCONNECTIONS (main.nim:429). This module reproduces that *distribution*
deterministically (seeded per run, SURVEY.md §7 RNG note) and lays the result
out TPU-first:

  conns[p, i]  int32  — i-th neighbor of peer p, -1 padding (capacity C)
  rev[p, i]    int32  — slot j such that conns[conns[p, i], j] == p
  out_mask[p,i] bool  — True iff p dialed that neighbor (outbound, for D_out)
  degree[p]    int32

The reverse-slot map makes every graft/prune *reciprocal* update a single
fixed-shape scatter (mesh_mask[q, rev] = v) with no collision handling — the
key trick that lets the whole GossipSub control plane run under jit.

Built host-side in numpy once per experiment epoch (the reference dials once
at startup, main.nim:466-471); everything steady-state runs on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _stable_group_ranks(keys: np.ndarray):
    """(order, first, ranks): stable sort order, group-start flags in sorted
    order, and each element's occurrence rank among equal keys in ARRAY
    order — the shared core of the two ranking entry points below."""
    m = len(keys)
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    first = np.ones(m, dtype=bool)
    first[1:] = sorted_keys[1:] != sorted_keys[:-1]
    group_start = np.maximum.accumulate(np.where(first, np.arange(m), 0))
    ranks = np.empty(m, dtype=np.int64)
    ranks[order] = np.arange(m) - group_start
    return order, first, ranks


def _cumcount(keys: np.ndarray) -> np.ndarray:
    """Occurrence rank of each element among equal keys, in array order."""
    return _stable_group_ranks(keys)[2]


def _cumcount_and_filtered(keys: np.ndarray, cap: int, half: int):
    """One-sort version of the build's two ranking passes.

    Returns (ok, slot_full) where ok marks edges whose BOTH endpoint
    occurrences rank below `cap` (keys holds the src half then the dst
    half, `half` elements each), and slot_full[i] is the occurrence rank of
    keys[i] among the KEPT occurrences — bit-identical to running _cumcount
    again on the filtered arrays, without the second 40M-element argsort
    (the kept elements keep their relative order, so their kept-prefix
    count within each key group IS their filtered cumcount)."""
    m = len(keys)
    order, first, ranks = _stable_group_ranks(keys)
    ok = (ranks[:half] < cap) & (ranks[half:] < cap)

    kept_sorted = np.concatenate([ok, ok])[order]
    c = np.cumsum(kept_sorted)
    before = c - kept_sorted                    # kept strictly before, global
    base = np.maximum.accumulate(np.where(first, before, 0))  # ... at group start
    slot_full = np.empty(m, dtype=np.int64)
    slot_full[order] = before - base            # kept-prefix within the group
    return ok, slot_full


def sample_dials(n: int, connect_to: int, seed: int) -> np.ndarray:
    """dials[p] = the connect_to distinct peers (!= p) that p dials.

    Matches the reference's per-peer independent shuffle-and-take
    (main.nim:376-381). Exact row permutation for small n; rejection sampling
    for large n (collision probability ~ connect_to^2/n)."""
    rng = np.random.default_rng(seed)
    if n <= 4096:
        r = rng.random((n, n))
        np.fill_diagonal(r, np.inf)
        return np.argsort(r, axis=1)[:, :connect_to].astype(np.int64)

    k = connect_to
    draw = max(2 * k + 8, k + 16)
    # NOTE: the draw must stay int64 — the generator's output stream depends
    # on the requested dtype, and graph construction is fingerprinted
    # (runtime/checkpoint.py); narrow AFTER drawing
    cand = rng.integers(0, n - 1, size=(n, draw))
    me = np.arange(n)[:, None]
    cand = np.where(cand >= me, cand + 1, cand).astype(np.int32)
    # ^ uniform over [0..n)\{me}; int32 for the row sort below
    # take the first k distinct per row. "Duplicate" = an equal value
    # appeared EARLIER in the row; a stable row sort puts the earliest
    # occurrence first within each equal run, so flagging equal-to-
    # predecessor in sorted order and scattering back marks exactly the
    # later occurrences (O(n·draw·log draw), vs the old per-column loop's
    # O(n·draw²) — ~2 s faster at 1M).
    ordr = np.argsort(cand, axis=1, kind="stable")
    srt = np.take_along_axis(cand, ordr, axis=1)
    dup_sorted = np.concatenate(
        [np.zeros((n, 1), bool), srt[:, 1:] == srt[:, :-1]], axis=1)
    dup = np.empty_like(dup_sorted)
    np.put_along_axis(dup, ordr, dup_sorted, axis=1)
    keep_rank = np.cumsum(~dup, axis=1) - 1
    out = np.full((n, k), -1, dtype=np.int64)
    rows, cols = np.nonzero(~dup & (keep_rank < k))
    out[rows, keep_rank[rows, cols]] = cand[rows, cols]
    # rows that still have holes (astronomically rare): fill with (p+1+i) mod n
    holes = out < 0
    if holes.any():
        hr, hc = np.nonzero(holes)
        out[hr, hc] = (hr + 1 + hc) % n
    return out


@dataclass
class ConnGraph:
    conns: np.ndarray      # (N, C) int32, -1 padded
    rev: np.ndarray        # (N, C) int32, -1 padded
    out_mask: np.ndarray   # (N, C) bool
    degree: np.ndarray     # (N,) int32

    @property
    def n(self) -> int:
        return int(self.conns.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.conns.shape[1])

    def validate(self) -> None:
        """Reverse-map invariant: conns[conns[p,i], rev[p,i]] == p."""
        p, i = np.nonzero(self.conns >= 0)
        q = self.conns[p, i]
        j = self.rev[p, i]
        assert (self.conns[q, j] == p).all(), "reverse-slot map broken"


def build_connection_graph(
    n: int,
    connect_to: int,
    seed: int = 0,
    max_degree: int | None = None,
    dials: np.ndarray | None = None,
) -> ConnGraph:
    """Symmetrize per-peer dials into padded neighbor lists + reverse map.

    max_degree plays MAXCONNECTIONS (main.nim:429): an edge is kept only if
    both endpoints still have a free slot, in random edge order — mirroring
    dial-time rejection by a full peer."""
    if dials is None:
        dials = sample_dials(n, connect_to, seed)
    k = dials.shape[1]
    if max_degree is None:
        # expected degree = 2*connect_to; generous slack keeps rejections rare
        max_degree = min(max(4 * k, 16), max(n - 1, 1))
    cap = max_degree

    # int32 endpoint ids: the stable argsorts below are the build's hot spot
    # and sort ~2x faster on the narrower dtype (peer ids fit easily)
    src = np.repeat(np.arange(n, dtype=np.int32), k)
    dst = dials.reshape(-1).astype(np.int32)
    lo, hi = np.minimum(src, dst), np.maximum(src, dst)
    # dedupe undirected pairs, keeping the first dialer as the outbound side
    # (pair key needs the full int64 range: n^2 ids)
    pair_key = lo.astype(np.int64) * n + hi
    _, first_idx = np.unique(pair_key, return_index=True)
    first_idx.sort()
    e_src, e_dst = src[first_idx], dst[first_idx]

    # random edge order, then capacity filter (both endpoints must have room)
    rng = np.random.default_rng(seed + 0x5EED)
    order = rng.permutation(len(e_src))
    e_src, e_dst = e_src[order], e_dst[order]
    # a node occupies one slot per incident edge regardless of direction, so
    # slot ranks count appearances across BOTH endpoint arrays; the src copy
    # of edge e sits at position e, the dst copy at position E + e, keeping
    # slot order aligned with edge order
    m = len(e_src)
    ok, slot_full = _cumcount_and_filtered(
        np.concatenate([e_src, e_dst]), cap, m)
    slot_src, slot_dst = slot_full[:m][ok], slot_full[m:][ok]
    e_src, e_dst = e_src[ok], e_dst[ok]

    conns = np.full((n, cap), -1, dtype=np.int32)
    rev = np.full((n, cap), -1, dtype=np.int32)
    out = np.zeros((n, cap), dtype=bool)
    conns[e_src, slot_src] = e_dst
    conns[e_dst, slot_dst] = e_src
    rev[e_src, slot_src] = slot_dst
    rev[e_dst, slot_dst] = slot_src
    out[e_src, slot_src] = True  # dialer side is the outbound connection
    degree = (conns >= 0).sum(axis=1).astype(np.int32)
    return ConnGraph(conns=conns, rev=rev, out_mask=out, degree=degree)
