"""On-device flight recorder: opt-in per-heartbeat telemetry channels.

The reference harness's observability contract stops at CUMULATIVE counters
(latency lines + a Prometheus scrape of end-state totals, SURVEY §0); the
per-round dynamics — the coverage/score curves arXiv:2007.02754 uses to
characterize attacks — are invisible. This module records them ON DEVICE:
`telemetry_observables` reduces the live SimState to a fixed set of
per-round channels, and the scan runners stack them into a fixed-shape
(n_heartbeats, K) trace alongside their existing obs dicts.

The arming contract follows ops/faults.py exactly:

  * `TelemetryParams` is a frozen (hashable) dataclass passed as a STATIC
    jit argument. `record=False` (the default) means the recorder does not
    exist: `run_recorded_heartbeats` literally delegates to
    `run_heartbeats` — the same function, the same jit cache entry, the
    same output buffers — and the attack/fault/recovery runners take
    `telemetry=None` on exactly the pre-recorder trace. Bit-identity is
    pinned by tests/test_telemetry.py.
  * Armed, the channels are pure reductions over state the scan body
    already holds — no PRNG is consumed, no state leaf is written, so the
    protocol trajectory is bit-identical armed or not; only the scan's
    OUTPUT grows the tel_* keys.
  * Sharding is free: every channel is a full-array reduction (or a
    small-vector reduction) over the peer axis, so under the nested
    trials x peers grid (parallel/sharding.py) GSPMD inserts per-group
    partial reductions and the (steps,) curves land trial-sharded like
    the rest of the obs dict, gathered at unstack.

Channel catalog (K columns of the flight-recorder window; all float32):

  tel_mesh_coverage    fraction of live subscribed peers with >= 1 mesh edge
  tel_mean_degree      mean mesh degree over live subscribed peers
  tel_degree_hist      (degree_bins,) mesh-degree histogram, normalized;
                       last bin catches degree >= degree_bins - 1
  tel_score_q          (len(quantiles),) score quantiles over valid
                       directed edges (exact under the deferred-decay
                       protocol — the scales are applied on the fly)
  tel_graylisted_frac  fraction of valid edges scoring below the graylist
                       threshold (ALL edges — the attack obs key of the
                       same name is restricted to honest->attacker edges)
  tel_bytes_tx/rx      cumulative traffic totals (per-round deltas are a
                       host-side diff of the curve)
  tel_ihave/tel_iwant  cumulative IHAVE/IWANT control messages sent
  tel_queue_depth_ms   mean uplink backlog: max(uplink_free - t, 0) over
                       live subscribed peers (the answer-queue depth the
                       iwant_spam attack drives)
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .heartbeat import _apply_decay, heartbeat_step, run_heartbeats
from .pull import neighbor_pull_bool
from .state import (SimParams, SimState, repair_inert, restore_repair,
                    strip_repair)


@dataclass(frozen=True)
class TelemetryParams:
    """Static flight-recorder configuration (hashable -> jit static arg).

    `record=False` disables the recorder entirely: the runners delegate to
    their un-instrumented counterparts and no telemetry code is traced."""

    record: bool = False
    # mesh-degree histogram bins: [0, 1, .., degree_bins-2, >=degree_bins-1]
    degree_bins: int = 12
    # score quantiles over valid directed edges (fractions in [0, 1])
    quantiles: tuple = (0.1, 0.5, 0.9)

    @property
    def enabled(self) -> bool:
        return self.record

    def validate(self) -> None:
        if self.degree_bins < 2:
            raise ValueError(
                f"degree_bins must be >= 2, got {self.degree_bins}")
        if not self.quantiles:
            raise ValueError("need at least one score quantile")
        for q in self.quantiles:
            if not (0.0 <= q <= 1.0):
                raise ValueError(f"quantile {q} outside [0, 1]")


def telemetry_observables(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    params: SimParams,
    telemetry: TelemetryParams,
    batch_factor: int = 1,
    valid: jnp.ndarray | None = None,
    decay_scales=None,
    deg: jnp.ndarray | None = None,
) -> dict:
    """One round's telemetry channels as a dict of f32 scalars/vectors.

    `valid`: the (N, C) edge-validity conjunction when the caller already
    holds it (hoisted scans); recomputed otherwise. `decay_scales`: the
    deferred-decay (fmd_scale, slow_scale) pair — scores are reconstructed
    exactly as heartbeat_step's _score_now does, so recorded quantiles
    match the per-step-decayed values bit-for-bit. `deg`: the carried (N,)
    mesh degree when the carried-degree protocol holds (mesh ⊆ valid);
    requires `valid`."""
    live = state.alive & state.subscribed
    if valid is None:
        if deg is not None:
            raise ValueError("deg requires valid (the carried-degree "
                             "protocol's hoisted validity mask)")
        nbr_ok = neighbor_pull_bool(live, conns, rev, batch_factor)
        valid = ((conns >= 0) & state.alive[:, None] & nbr_ok
                 & state.subscribed[:, None])
    if deg is None:
        mesh = state.mesh_mask & valid
        deg = mesh.sum(axis=-1)
    else:
        mesh = state.mesh_mask  # caller guarantees mesh ⊆ valid
    f32 = jnp.float32
    n_live = jnp.maximum(live.sum(), 1).astype(f32)

    if decay_scales is not None:
        f_sc, s_sc = decay_scales
        sc = state.replace(
            fmd=_apply_decay(state.fmd, f_sc, params),
            slow_penalty=_apply_decay(state.slow_penalty, s_sc, params),
        ).score(params)
    else:
        sc = state.score(params)

    b = telemetry.degree_bins
    idx = jnp.clip(deg, 0, b - 1)
    # one-hot-compare histogram (no scatter: the (N, b) compare reduces
    # over the peer axis, which is what shards under the nested grid)
    hist = ((idx[:, None] == jnp.arange(b)) & live[:, None]).sum(axis=0)
    qs = jnp.asarray(telemetry.quantiles, dtype=f32)
    scv = jnp.where(valid, sc, jnp.nan)
    n_edges = jnp.maximum(valid.sum(), 1).astype(f32)
    backlog = jnp.maximum(state.uplink_free_ms - state.t_ms, 0.0)
    return {
        "tel_mesh_coverage": (live & (deg >= 1)).sum() / n_live,
        "tel_mean_degree": jnp.where(live, deg, 0).sum() / n_live,
        "tel_degree_hist": hist.astype(f32) / n_live,
        "tel_score_q": jnp.nanquantile(scv, qs).astype(f32),
        "tel_graylisted_frac": (
            (valid & (sc < params.graylist_threshold)).sum() / n_edges),
        "tel_bytes_tx": state.bytes_tx.sum().astype(f32),
        "tel_bytes_rx": state.bytes_rx.sum().astype(f32),
        "tel_ihave": state.ihave_tx.sum().astype(f32),
        "tel_iwant": state.iwant_tx.sum().astype(f32),
        "tel_queue_depth_ms": jnp.where(live, backlog, 0.0).sum() / n_live,
    }


def adaptive_observables(
    state: SimState,
    ctrl,
    attacker: jnp.ndarray,
    acting: jnp.ndarray,
    violations: jnp.ndarray,
) -> dict:
    """Attacker-side controller channels for the ADAPTIVE adversary
    (ops/adversary.py adaptive_round) — the recorder discipline applies:
    pure reductions over state the scan body already holds, no PRNG, no
    state write; only the armed scan's OUTPUT grows these keys. All f32
    scalars:

      adv_violation_rate    protocol violations accrued THIS round per
                            attacker (the live rate the duty cycle is
                            throttling; ~0 while the controller coasts)
      adv_throttled_frac    fraction of the cohort duty-cycled OFF this
                            round
      adv_regraft_attempts  cumulative backoff-expiry re-grafts sent
      adv_px_sybil_frac     fraction of OCCUPIED honest px_pool entries
                            holding attacker ids — how poisoned the repair
                            candidate lattice currently is (0.0 when the
                            repair leaves are stripped: nothing reads the
                            pool either)

    `ctrl` is the ops/state.AdaptiveCtrl carry; `acting` the (N,) bool
    flood mask the duty cycle chose; `violations` the round's scalar
    violation count."""
    f32 = jnp.float32
    n_att = jnp.maximum(attacker.sum(), 1).astype(f32)
    if state.px_pool is not None:
        honest = ~attacker & state.alive & state.subscribed
        occ = (state.px_pool >= 0) & honest[:, None]
        sybil = occ & attacker[jnp.clip(state.px_pool, 0)]
        px_sybil_frac = sybil.sum() / jnp.maximum(occ.sum(), 1).astype(f32)
    else:
        px_sybil_frac = f32(0.0)
    return {
        "adv_violation_rate": violations.astype(f32) / n_att,
        "adv_throttled_frac": (attacker & ~acting).sum() / n_att,
        "adv_regraft_attempts": ctrl.regrafts.sum().astype(f32),
        "adv_px_sybil_frac": px_sybil_frac,
    }


def run_recorded_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    steps: int,
    telemetry: TelemetryParams | None = None,
    batch_factor: int = 1,
):
    """run_heartbeats with the flight recorder: returns (state, trace) where
    trace maps each tel_* channel to a (steps,) or (steps, k) curve.

    Disabled (`telemetry` None or record=False) this IS run_heartbeats —
    the same call, the same jit cache entry, the same output buffers — and
    the trace is {}. Armed, the scan preserves run_heartbeats' protocols
    exactly (hoisted validity, carried degree, deferred decay: the recorded
    scores apply the running scales on the fly), so the final state is
    bit-identical to the untraced runner; only the outputs grow."""
    if telemetry is None or not telemetry.enabled:
        return run_heartbeats(state, conns, rev, out_mask, params, steps), {}
    telemetry.validate()
    if repair_inert(params):
        state, saved = strip_repair(state)
        out, trace = _run_recorded_heartbeats(
            state, conns, rev, out_mask, params, telemetry, steps,
            batch_factor)
        return restore_repair(out, saved), trace
    return _run_recorded_heartbeats(
        state, conns, rev, out_mask, params, telemetry, steps, batch_factor)


@partial(jax.jit,
         static_argnames=("params", "telemetry", "steps", "batch_factor"))
def _run_recorded_heartbeats(
    state: SimState,
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    out_mask: jnp.ndarray,
    params: SimParams,
    telemetry: TelemetryParams,
    steps: int,
    batch_factor: int = 1,
):
    # mirror of ops/heartbeat._run_heartbeats with a per-round telemetry
    # emission — the hoist/carry/deferral decisions must stay in lockstep
    # (the bit-identity tests compare final states across the two)
    nbr_ok = None
    valid_pre = None
    if params.churn_down_per_hb == 0.0 and params.churn_up_per_hb == 0.0:
        nbr_ok = neighbor_pull_bool(
            state.alive & state.subscribed, conns, rev, batch_factor)
        valid_pre = ((conns >= 0) & state.alive[:, None] & nbr_ok
                     & state.subscribed[:, None])

    one = jnp.float32(1.0)
    if valid_pre is not None:
        mesh0 = state.mesh_mask & valid_pre
        state = state.replace(mesh_mask=mesh0)

        def body(carry, _):
            s, deg, f_sc, s_sc = carry
            s, deg = heartbeat_step(
                s, conns, rev, out_mask, params, batch_factor=batch_factor,
                nbr_ok=nbr_ok, valid_pre=valid_pre,
                decay_scales=(f_sc, s_sc), deg_in=deg)
            f2, s2 = f_sc * params.fmd_decay, s_sc * params.slow_decay
            # post-step the effective decay scale is the UPDATED carry (the
            # step defers its own end-of-round decay into it)
            obs = telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor,
                valid=valid_pre, decay_scales=(f2, s2), deg=deg)
            return (s, deg, f2, s2), obs

        (state, _, f_sc, s_sc), trace = jax.lax.scan(
            body, (state, mesh0.sum(axis=-1), one, one), None, length=steps)
    else:
        def body(carry, _):
            s, f_sc, s_sc = carry
            s = heartbeat_step(
                s, conns, rev, out_mask, params, batch_factor=batch_factor,
                nbr_ok=nbr_ok, valid_pre=valid_pre,
                decay_scales=(f_sc, s_sc))
            f2, s2 = f_sc * params.fmd_decay, s_sc * params.slow_decay
            obs = telemetry_observables(
                s, conns, rev, params, telemetry, batch_factor=batch_factor,
                decay_scales=(f2, s2))
            return (s, f2, s2), obs

        (state, f_sc, s_sc), trace = jax.lax.scan(
            body, (state, one, one), None, length=steps)
    state = state.replace(
        fmd=_apply_decay(state.fmd, f_sc, params),
        slow_penalty=_apply_decay(state.slow_penalty, s_sc, params),
    )
    return state, trace
