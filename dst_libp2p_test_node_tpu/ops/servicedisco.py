"""Service discovery over the DHT: advertise/lookup as batched array ops.

The reference service-discovery node (nim-test-node/service-discovery/
{main,core,env,helpers}.nim) exercises libp2p's service_discovery protocol on
top of kad-dht: RoleAdvertiser nodes `startAdvertising(ServiceInfo(id,data))`
(core.nim:7-16), RoleDiscoverer nodes run a periodic `lookup(hashServiceId)`
loop logging advertisement counts and unique providers (core.nim:30-53),
RoleHybrid does both, RoleBootstrap anchors the DHT. Tunables: safetyParam,
ipSimCoefficient, advertExpiry, xprPublishing (env.nim:120-140).

TPU-native design on the ops/kad substrate:

  service keys    hash of the service id string -> the same 128-bit keyspace
                  as node keys (host-side, stable across runs)
  advert store    (N, A) record slots per node: provider id, service index,
                  seqNo, expiry timestamp — fixed capacity, expired slots
                  are reusable (the array analog of the provider record TTL)
  advertise wave  one find_node() toward the service key per (advertiser,
                  service), then a scatter of provider records into the R
                  closest nodes' stores, R = k_store * (1 + safetyParam)
                  (the safety widening), with ipSimCoefficient demoting
                  same-stage replicas (the IP-similarity spread heuristic —
                  modeled: stage is our IP-locality analog)
  lookup wave     the FULL request/response machinery per (discoverer,
                  service): an iterative shortlist walk with ALPHA
                  requests per wave where dead nodes cost a per-query
                  timeout (no liveness oracle), live responders piggyback
                  matching provider records, providers dedup ACROSS waves
                  (core.nim:40-52's HashSet), and a lookup past its
                  deadline fails (core.nim:36-38's valueOr branch) — see
                  lookup() below

Latency accounting: advertise cost = the underlying lookup's RTT walk plus
one more round trip to store records; lookup cost = the walk's accumulated
wave times including timeout stalls. xprPublishing toggles the record
payload size used for byte accounting (extended peer records carry
addresses; core ads only the peer id).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from . import kad


def service_key(service_id: str) -> np.ndarray:
    """hashServiceId: a stable 128-bit key for a service id string."""
    h = hashlib.sha256(service_id.encode()).digest()
    return np.frombuffer(h[:16], dtype=">u4").astype(np.uint32)


# record payload sizes for byte accounting (xprPublishing, env.nim:138-140)
AD_BYTES_CORE = 64       # peerId + seqNo + signature envelope
AD_BYTES_XPR = 256       # extended peer record: + addresses


@dataclass(frozen=True)
class SDParams:
    """Static service-discovery parameters (env.nim:120-184 surface)."""

    k_store: int = 8                 # base replication of provider records
    safety_param: float = 0.0        # SD_SAFETY_PARAM: widens replication
    ip_sim_coefficient: float = 0.0  # SD_IP_SIM_COEFF: same-stage demotion
    advert_expiry_ms: float = 900_000.0  # SD_ADVERT_EXPIRY_SECONDS default
    xpr_publishing: bool = True      # SD_XPR_PUBLISHING
    # request machinery: a request to an unresponsive node stalls its wave
    # by this much before the walk moves on (the discoverer has no liveness
    # oracle); a whole lookup past the deadline fails — 30 s mirrors the
    # kad probe's findNode(...).wait(30s) convention (kad-dht/core.nim:44)
    query_timeout_ms: float = 5_000.0
    lookup_deadline_ms: float = 30_000.0

    @property
    def replication(self) -> int:
        return max(1, int(round(self.k_store * (1.0 + self.safety_param))))

    @property
    def ad_bytes(self) -> int:
        return AD_BYTES_XPR if self.xpr_publishing else AD_BYTES_CORE


@struct.dataclass
class AdvertStore:
    """Per-node provider-record store (fixed capacity A per node)."""

    provider: jnp.ndarray   # (N, A) int32, -1 empty
    service: jnp.ndarray    # (N, A) int32 service index
    seq_no: jnp.ndarray     # (N, A) int32
    expires_ms: jnp.ndarray  # (N, A) float32


def init_advert_store(n: int, capacity: int = 64) -> AdvertStore:
    return AdvertStore(
        provider=jnp.full((n, capacity), -1, jnp.int32),
        service=jnp.full((n, capacity), -1, jnp.int32),
        seq_no=jnp.zeros((n, capacity), jnp.int32),
        expires_ms=jnp.zeros((n, capacity), jnp.float32),
    )


def _store_one(store_row, now_ms, provider, service, seq_no, expiry_ms, write):
    """Insert/refresh one provider record in one node's store row.

    Same (provider, service) refreshes in place (seqNo bump, new expiry);
    otherwise the record takes the first free-or-expired slot; a full store
    drops the record (bounded capacity is the DoS guard the reference
    inherits from the provider-record TTL store)."""
    prov, svc, seq, exp = store_row
    match = (prov == provider) & (svc == service)
    free = (prov < 0) | (exp <= now_ms)
    has_match = match.any()
    # first matching slot, else first free slot
    slot_match = jnp.argmax(match)
    slot_free = jnp.argmax(free)
    slot = jnp.where(has_match, slot_match, slot_free)
    ok = write & (has_match | free.any())
    a = prov.shape[0]
    idx = jnp.where(ok, slot, a)
    prov = prov.at[idx].set(provider, mode="drop")
    svc = svc.at[idx].set(service, mode="drop")
    seq = seq.at[idx].set(seq_no, mode="drop")
    exp = exp.at[idx].set(now_ms + expiry_ms, mode="drop")
    return prov, svc, seq, exp


@partial(jax.jit, static_argnames=("params",))
def advertise(
    store: AdvertStore,
    kstate: kad.KadState,
    advertisers: jnp.ndarray,    # (Q,) int32 distinct advertiser peers
    service_idx: jnp.ndarray,    # (Q,) int32 service index per advertiser
    service_keys: jnp.ndarray,   # (S, W) uint32 key per service index
    seq_no: jnp.ndarray,         # (Q,) int32 current sequence numbers
    stage: jnp.ndarray,
    lat_ms: jnp.ndarray,
    now_ms,
    params: SDParams,
) -> tuple[AdvertStore, kad.KadState, jnp.ndarray]:
    """One advertise wave: locate the R closest nodes to each service key and
    place provider records there. Returns (store, kstate, wave_latency_ms)."""
    targets = service_keys[service_idx]
    res, kstate = kad.find_node(kstate, advertisers, targets, stage, lat_ms)
    closest = res.closest                        # (Q, K_RESP)

    # replica selection: closest first, same-stage-as-advertiser entries
    # demoted by ipSimCoefficient (stage = IP-locality analog)
    q = advertisers.shape[0]
    k = closest.shape[1]
    base_rank = jnp.broadcast_to(
        jnp.arange(k, dtype=jnp.float32)[None, :], (q, k)
    )
    same_stage = stage[jnp.clip(closest, 0)] == stage[advertisers][:, None]
    demoted = base_rank + params.ip_sim_coefficient * same_stage * k
    demoted = jnp.where(closest >= 0, demoted, jnp.float32(1e9))
    order = jnp.argsort(demoted, axis=-1)
    replicas = jnp.take_along_axis(closest, order, axis=-1)[
        :, : params.replication
    ]                                            # (Q, R)

    # scatter records into replica stores, grouped by storing node
    flat_node = replicas.reshape(-1)
    flat_prov = jnp.broadcast_to(
        advertisers[:, None], replicas.shape
    ).reshape(-1)
    flat_svc = jnp.broadcast_to(
        service_idx[:, None], replicas.shape
    ).reshape(-1)
    flat_seq = jnp.broadcast_to(seq_no[:, None], replicas.shape).reshape(-1)

    def apply_event(i, rows):
        prov, svc, seq, exp = rows
        node = flat_node[i]
        ok = node >= 0
        nrow = jnp.clip(node, 0)
        new = _store_one(
            (prov[nrow], svc[nrow], seq[nrow], exp[nrow]),
            now_ms, flat_prov[i], flat_svc[i], flat_seq[i],
            params.advert_expiry_ms, ok,
        )
        return (
            prov.at[nrow].set(jnp.where(ok, new[0], prov[nrow])),
            svc.at[nrow].set(jnp.where(ok, new[1], svc[nrow])),
            seq.at[nrow].set(jnp.where(ok, new[2], seq[nrow])),
            exp.at[nrow].set(jnp.where(ok, new[3], exp[nrow])),
        )

    rows = (store.provider, store.service, store.seq_no, store.expires_ms)
    # sequential fori over store events: events can collide on a node, and
    # the per-wave event count (Q*R) is small; each step is a tiny gather +
    # scatter, so the scan stays on-device with no host sync
    rows = jax.lax.fori_loop(0, flat_node.shape[0], apply_event, rows)
    store = AdvertStore(
        provider=rows[0], service=rows[1], seq_no=rows[2], expires_ms=rows[3]
    )

    # advertise latency = lookup walk + one store round trip to the farthest
    # chosen replica
    rep_lat = 2.0 * lat_ms[stage[advertisers][:, None],
                           stage[jnp.clip(replicas, 0)]]
    rep_lat = jnp.where(replicas >= 0, rep_lat, 0.0)
    wave_ms = res.latency_ms + rep_lat.max(axis=-1)
    return store, kstate, wave_ms


@struct.dataclass
class SDLookupResult:
    advertisements: jnp.ndarray  # (Q,) int32 record copies retrieved
    unique_peers: jnp.ndarray    # (Q,) int32 distinct providers, deduped
    #                              across ALL response waves of the lookup
    latency_ms: jnp.ndarray      # (Q,) float32 wall time incl. timeouts
    ok: jnp.ndarray              # (Q,) bool — False: deadline exceeded,
    #                              counts zeroed (runLookupLoop's valueOr
    #                              failure branch, core.nim:36-38)
    timeouts: jnp.ndarray        # (Q,) int32 requests that timed out


@partial(jax.jit, static_argnames=("params", "rounds", "shortlist"))
def lookup(
    store: AdvertStore,
    kstate: kad.KadState,
    discoverers: jnp.ndarray,    # (Q,) int32
    service_idx: jnp.ndarray,    # (Q,) int32
    service_keys: jnp.ndarray,   # (S, W) uint32
    stage: jnp.ndarray,
    lat_ms: jnp.ndarray,
    now_ms,
    params: SDParams,
    rounds: int = 6,
    shortlist: int = 32,
) -> tuple[SDLookupResult, kad.KadState]:
    """One lookup per discoverer (runLookupLoop body, core.nim:30-53), as
    the full request/response machinery rather than an oracle walk:

      - iterative waves toward the service key, ALPHA requests per wave
        (the shortlist walk of kad.find_node);
      - the discoverer cannot observe liveness, so a request to a dead
        node stalls its wave by `query_timeout_ms` before the walk moves
        on (per-query timeout; kad.find_node's oracle alive-filter is the
        thing this machinery replaces);
      - every live responder piggybacks its matching unexpired provider
        records on the response (GET_PROVIDERS-style), and providers are
        deduplicated ACROSS waves — a record fetched from three replicas
        in three different waves is three `advertisements` but one entry
        in `unique_peers` (core.nim:40-44's HashSet over ad.data.peerId);
      - a lookup whose accumulated wall time exceeds
        `lookup_deadline_ms` FAILS: counts are zeroed and `ok` is False,
        the valueOr branch the reference logs as "Lookup failed" — and the
        walk ABORTS there (r4 advisor): waves past the deadline never
        start, so a failed lookup stops generating queries, learning and
        traffic the way runLookupLoop's deadline abort does.
    """
    n = kstate.rtable.shape[0]
    q = discoverers.shape[0]
    s = shortlist
    targets = service_keys[service_idx]
    o_stage = stage[discoverers]

    def response(peer, target_key):
        resp = kad._closest_from_table(
            kstate.rtable[peer], kstate.keys, target_key, kad.K_RESP)
        return jnp.where(kstate.alive[peer], resp, -1)

    sl0 = jax.vmap(
        lambda o, t: kad._closest_from_table(
            kstate.rtable[o], kstate.keys, t, s)
    )(discoverers, targets)

    def round_body(carry, _):
        sl, queried, t_acc, nq, nto, ads, pmask = carry
        d = kad._dist(kstate.keys, sl, targets)
        order = kad.lex_argsort(d)
        rank = jnp.argsort(order, axis=-1)
        # request/response semantics: NO alive filter here — the
        # discoverer finds out a peer is dead by timing out on it
        cand = (sl >= 0) & ~queried & (sl != discoverers[:, None])
        head_unqueried = (cand & (rank < kad.K_RESP)).any(axis=-1)
        cand = cand & head_unqueried[:, None]
        # deadline abort (r4 advisor): runLookupLoop stops AT the deadline,
        # so a wave starting past the budget never happens — no queries, no
        # routing-table learning, no traffic counters. Granularity is the
        # wave: the wave that CROSSES the deadline completes (its requests
        # were already in flight when the timer fired), later waves don't
        # start.
        cand = cand & (t_acc < params.lookup_deadline_ms)[:, None]
        pick, p_ids = kad._pick_alpha(sl, rank, cand, s)
        any_pick = pick.any(axis=-1)
        p_live = (p_ids >= 0) & kstate.alive[jnp.clip(p_ids, 0)]

        resp = jax.vmap(jax.vmap(response, in_axes=(0, None)))(
            jnp.clip(p_ids, 0), targets
        )                                                 # (Q, ALPHA, K_RESP)
        resp = jnp.where((p_ids >= 0)[..., None], resp, -1)

        # per-request cost: RTT for live responders, the request timeout
        # for dead ones; the wave waits for its slowest outstanding request
        rtt = (2.0 * lat_ms[o_stage[:, None], stage[jnp.clip(p_ids, 0)]]
               + kad.PROC_MS)
        cost = jnp.where(p_live, rtt, params.query_timeout_ms)
        cost = jnp.where(p_ids >= 0, cost, 0.0)
        round_ms = cost.max(axis=-1)

        # GET_PROVIDERS piggyback: live responders return their matching
        # unexpired records; the (Q, N) mask dedups providers across waves
        rows = jnp.clip(p_ids, 0)
        rprov = store.provider[rows]                      # (Q, ALPHA, A)
        rvalid = (p_live[..., None] & (rprov >= 0)
                  & (store.service[rows] == service_idx[:, None, None])
                  & (store.expires_ms[rows] > now_ms))
        ads = ads + rvalid.sum(axis=(-1, -2)).astype(jnp.int32)
        flat_p = jnp.where(rvalid, rprov, n).reshape(q, -1)
        pmask = jax.vmap(
            lambda m, ids: m.at[ids].set(True, mode="drop")
        )(pmask, flat_p)

        # shortlist merge — the same helper find_node's round uses
        sl_new, q_new = kad._merge_shortlist(
            kstate.keys, sl, queried, pick, resp, targets, s)

        t_acc = t_acc + jnp.where(any_pick, round_ms, 0.0)
        nq = nq + (p_ids >= 0).sum(axis=-1)
        nto = nto + ((p_ids >= 0) & ~p_live).sum(axis=-1)
        return (sl_new, q_new, t_acc, nq, nto, ads, pmask), p_ids

    zeros_i = jnp.zeros((q,), jnp.int32)
    (sl, _, t_acc, nq, nto, ads, pmask), picked_seq = jax.lax.scan(
        round_body,
        (sl0, jnp.zeros((q, s), bool), jnp.zeros((q,), jnp.float32),
         zeros_i, zeros_i, zeros_i, jnp.zeros((q, n), bool)),
        None,
        length=rounds,
    )
    picked_seq = jnp.moveaxis(picked_seq, 0, 1).reshape(q, -1)

    # deadline: a lookup that ran past the budget FAILED — it reports
    # nothing (valueOr -> continue), though the network traffic happened.
    # STRICT comparison: the worst all-timeout walk costs exactly
    # rounds * query_timeout_ms = the default deadline, and that walk
    # (every wave stalled by dead nodes) must fail, not squeak through
    ok = t_acc < params.lookup_deadline_ms
    uniq = pmask.sum(axis=-1).astype(jnp.int32)
    ads = jnp.where(ok, ads, 0)
    uniq = jnp.where(ok, uniq, 0)

    # learning + accounting (as kad.find_node): the origin learns its final
    # shortlist; LIVE queried peers learn the origin; counters advance
    kstate = kad.rtable_insert(kstate, discoverers, sl)
    flat_peers = picked_seq.reshape(-1)
    flat_origin = jnp.broadcast_to(
        discoverers[:, None], picked_seq.shape).reshape(-1)
    live_ok = kstate.alive[jnp.clip(flat_peers, 0)]
    kstate = kad._teach_learners(kstate, flat_peers, flat_origin, live_ok)
    served = jnp.zeros((n,), jnp.int32).at[
        jnp.where((flat_peers >= 0) & live_ok, flat_peers, n)
    ].add(1, mode="drop")
    kstate = kstate.replace(
        queries_tx=kstate.queries_tx.at[discoverers].add(nq),
        queries_rx=kstate.queries_rx + served,
    )

    out = SDLookupResult(
        advertisements=ads,
        unique_peers=uniq,
        latency_ms=t_acc,
        ok=ok,
        timeouts=nto,
    )
    return out, kstate


@jax.jit
def expire_sweep(store: AdvertStore, now_ms) -> AdvertStore:
    """Drop expired records (advertExpiry TTL) — run between waves."""
    live = (store.provider >= 0) & (store.expires_ms > now_ms)
    return AdvertStore(
        provider=jnp.where(live, store.provider, -1),
        service=jnp.where(live, store.service, -1),
        seq_no=jnp.where(live, store.seq_no, 0),
        expires_ms=jnp.where(live, store.expires_ms, 0.0),
    )
