"""Simulation parameter and state containers.

SimParams is a frozen (hashable) dataclass passed as a *static* jit argument —
every field participates in trace specialization, mirroring how the reference
bakes GossipSub params at startup (configureGossipsubParams,
gossipsub-queues/main.nim:252-332).

SimState is the peer-major device pytree: one row per simulated peer where the
reference runs one OS process per peer (shadow/topogen.py:102-122).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config.env import GossipSubParams

# Width of the per-peer PX candidate pool (SimState.px_pool). A CONSTANT, not
# a SimParams field: the pool is a state leaf, and keying its shape on a
# tunable would make checkpoints / stacked trial pytrees incompatible across
# repair configs. params.px_count (<= this) bounds how many entries a PRUNE
# actually fills; the rest stay -1.
PX_POOL_WIDTH = 8


@dataclass(frozen=True)
class SimParams:
    """Static simulation parameters (hashable -> jit static arg)."""

    n: int                      # PEERS
    capacity: int               # neighbor-list capacity C
    d: int = 6
    d_low: int = 4
    d_high: int = 8
    d_score: int = 4
    d_out: int = 3
    d_lazy: int = 6
    heartbeat_ms: float = 1000.0
    prune_backoff_ms: float = 60_000.0
    gossip_factor: float = 0.25
    history_gossip: int = 3     # mcache gossip window in heartbeats
    flood_publish: bool = True
    fmd_weight: float = 1.0     # firstMessageDeliveries topic params (main.nim:335-340)
    fmd_cap: float = 30.0
    fmd_decay: float = 0.9
    decay_to_zero: float = 0.01
    # slow-peer penalty + priority-queue drop model (main.nim:264-299).
    # libp2p scoring convention: penalty WEIGHTS are negative and multiply a
    # non-negative counter into the score; state.slow_penalty holds the
    # counter, score() applies the weight.
    slow_weight: float = 0.0          # GOSSIPSUB_SLOW_PEER_PENALTY_WEIGHT (<0)
    slow_threshold_ms: float = 2000.0  # ..._THRESHOLD (seconds in the env)
    slow_decay: float = 0.2            # ..._DECAY
    send_queue_cap: int = 1024         # MAX_LOW_PRIORITY_QUEUE_LEN: data msgs
    # v1.1 opportunistic grafting (main.nim:292); -10000 = disabled
    opportunistic_graft_threshold: float = -10000.0
    # v1.1 score thresholds. The reference COMMENTS these out
    # (main.nim:276-278,306-308), deferring to nim-libp2p's defaults — which
    # are these values. With the default non-negative score weights they can
    # never bind and the gating is statically removed from the compiled step.
    gossip_threshold: float = -100.0     # no IHAVE to peers scored below
    publish_threshold: float = -1000.0   # flood/fanout skips peers below
    graylist_threshold: float = -10000.0  # receiver ignores peers below
    proc_delay_ms: float = 2.0  # per-hop validation/processing latency
    # TCP slow-start transfer dynamics (ops/disseminate.py tcp_flights):
    # under Shadow the nodes run REAL TCP stacks
    # (regression/Dockerfile_amd64_shadow:3-11), so a transfer larger than
    # the initial congestion window needs multiple RTT-gated flights —
    # the first flight carries at most initcwnd_segments * mss_bytes
    # (Linux IW10, RFC 6928) and the window doubles each RTT. Messages are
    # seconds apart, so every transfer starts from a slow-start-restarted
    # (cold) window. slow_start=False removes the term (datagram-style
    # transports with no window, and A/B isolation in tests).
    slow_start: bool = True
    mss_bytes: int = 1460
    initcwnd_segments: int = 10
    # Exact answered-IWANT serialization in the DELIVERY fixpoint (r5).
    # Always exact in the accounting (answer-queue drains, answered sets,
    # attribution offers ride the serialized fold regardless); this flag
    # additionally REPAIRS the arrival times when a queued answer would
    # have been somebody's first delivery — which at heartbeat <
    # dissemination-span shapes (the 100k bench) is every message, at the
    # honest cost of extra fixpoint passes. False = keep the unserialized
    # arrival times in exactly those binding cases (the r4-and-earlier
    # approximation, error <= the answer queue wait, a few tx_ms) — an
    # A/B attribution knob for the bench, NOT the model of record.
    serialize_answers: bool = True
    fanout_ttl_ms: float = 60_000.0  # v1.1 fanoutTTL (libp2p default 60 s)
    max_relax_iters: int = 48   # bound on the earliest-arrival fixpoint
    # Warm-started fixpoints: seed each publish's phase-1 relaxation from
    # the previous message's arrival offsets re-based to the new publish
    # time (state.warm_offset_ms; INF = no usable carry). The seed is a
    # heuristic upper bound only, so the fixpoint carries a self-
    # consistency certificate: any peer left strictly below its supported
    # value triggers ONE cold from-INF rerun (a scalar lax.cond), making
    # the result bit-identical to a cold start unconditionally. False
    # (the default) removes the seed, the certificate and the cond from
    # the trace — the cond's untaken branch still costs a second compile
    # of the whole fast pipeline, which long publish loops amortize but
    # one-shot calls should not pay.
    warm_start: bool = False
    # Exact-repair engine selection (only read when serialize_answers=True):
    # "parallel_prefix" (default) runs the scan-free Jacobi refinement —
    # one answer-queue fold + one candidate pull per iteration, with the
    # serialized global-sort pipeline kept as an in-trace fallback cond for
    # the cases the fold cannot certify (interleaved announce rounds, cap
    # cut). "serial" forces the legacy global-sort outer iteration
    # everywhere — the reference implementation the prefix path is
    # bit/rtol-pinned against (tests/test_exact_prefix.py).
    answer_queue_mode: str = "parallel_prefix"
    # Packed dissemination constants (ARCHITECTURE §6): store the per-edge
    # RELATIVE cost tables of the receiver-side fixpoint formulation
    # (parallel/exchange.py RecvConstants) as bf16 and fold the validity
    # masks into the bf16 +inf sentinel, halving the memory-bound carry's
    # HBM traffic on the budget/sharded dispatch paths. Absolute-time
    # fields and the accounting fold stay f32 (bf16's 8-bit mantissa
    # resolves only ~4 s at a 1e6 ms sim clock). OFF by default: the ~2 ms
    # per-edge quantization is inside the bounded mode's error bar but
    # breaks the exact mode's model-of-record bit guarantees.
    packed_state: bool = False
    # Fused mega-round scan (ARCHITECTURE §18): run the whole
    # heartbeat-burst + publish round chain as ONE lax.scan over rounds —
    # one device dispatch per round instead of one per phase. OFF by
    # default: run_fused_rounds (ops/disseminate.py) literally delegates to
    # the phase-split run_heartbeats + disseminate chain (same jit cache
    # entries, zero retraces, zero extra PRNG splits, bit-identical). ON,
    # the fused body calls the SAME per-phase programs under one trace, so
    # delivery outcomes stay bitwise equal; float delays carry an rtol
    # because XLA may re-fuse arithmetic inside the scan body.
    fused_rounds: bool = False
    exclude_first_sender: bool = True   # don't forward back to the delivering peer
    idontwant_threshold_bytes: int = 1000  # go-test-node/main.go:165 (v1.2)
    churn_down_per_hb: float = 0.0  # P(alive peer dies) per heartbeat
    churn_up_per_hb: float = 0.0    # P(dead peer revives) per heartbeat
    # Mesh-repair subsystem (ops/repair.py + the opt-in heartbeat branches).
    # All OFF by default: the compiled default step contains none of the
    # repair ops and is bit-identical to the repair-free engine (pinned by
    # tests/test_repair.py).
    evict: bool = False                 # score-based mesh eviction branch
    eviction_threshold: float = -50.0   # PRUNE mesh members scoring below this
    px: bool = False                    # peer exchange on PRUNE
    px_count: int = 6                   # candidate ids per PRUNE (<= PX_POOL_WIDTH)
    redial: bool = False                # re-dial controller for starved peers
    redial_patience: int = 3            # heartbeats below d_low before dialing

    def validate(self) -> None:
        if not (0 < self.d_low <= self.d <= self.d_high <= self.capacity):
            raise ValueError(
                "require 0 < d_low <= d <= d_high <= capacity, got "
                f"{self.d_low} <= {self.d} <= {self.d_high} <= {self.capacity}"
            )
        if self.n < 2:
            raise ValueError("need at least 2 peers")
        if self.heartbeat_ms <= 0:
            raise ValueError("heartbeat_ms must be positive")
        if self.history_gossip < 1:
            raise ValueError(
                f"history_gossip must be >= 1, got {self.history_gossip}")
        if self.mss_bytes < 1 or self.initcwnd_segments < 1:
            raise ValueError("mss_bytes and initcwnd_segments must be >= 1")
        # the spec requires non-positive thresholds; enforcing it keeps the
        # static can-thresholds-bind compile decision sound (scores are
        # non-negative unless a negative weight is configured)
        for name in ("gossip_threshold", "publish_threshold",
                     "graylist_threshold"):
            if getattr(self, name) > 0:
                raise ValueError(f"{name} must be <= 0")
        if self.eviction_threshold > 0:
            # eviction is a score defense: a positive threshold would evict
            # well-behaved zero-scored peers every heartbeat
            raise ValueError("eviction_threshold must be <= 0")
        if not (1 <= self.px_count <= PX_POOL_WIDTH):
            raise ValueError(
                f"px_count must be in [1, {PX_POOL_WIDTH}], got {self.px_count}")
        if self.redial_patience < 1:
            raise ValueError("redial_patience must be >= 1")
        if self.answer_queue_mode not in ("parallel_prefix", "serial"):
            raise ValueError(
                "answer_queue_mode must be 'parallel_prefix' or 'serial', "
                f"got {self.answer_queue_mode!r}")

    @classmethod
    def from_gossipsub(
        cls, n: int, capacity: int, g: GossipSubParams, **overrides
    ) -> "SimParams":
        return cls(
            n=n,
            capacity=capacity,
            d=g.d,
            d_low=g.d_low,
            d_high=g.d_high,
            d_score=g.d_score,
            d_out=g.d_out,
            d_lazy=g.d_lazy,
            heartbeat_ms=float(g.heartbeat_ms),
            prune_backoff_ms=float(g.prune_backoff_sec) * 1000.0,
            gossip_factor=g.gossip_factor,
            history_gossip=g.history_gossip,
            flood_publish=g.flood_publish,
            fmd_weight=g.first_message_deliveries_weight,
            fmd_cap=g.first_message_deliveries_cap,
            fmd_decay=g.first_message_deliveries_decay,
            decay_to_zero=g.decay_to_zero,
            idontwant_threshold_bytes=g.idontwant_message_threshold,
            slow_weight=g.slow_peer_penalty_weight,
            slow_threshold_ms=g.slow_peer_penalty_threshold * 1000.0,
            slow_decay=g.slow_peer_penalty_decay,
            send_queue_cap=g.max_low_priority_queue_len,
            opportunistic_graft_threshold=g.opportunistic_graft_threshold,
            gossip_threshold=g.gossip_threshold,
            publish_threshold=g.publish_threshold,
            graylist_threshold=g.graylist_threshold,
            **overrides,
        )


@struct.dataclass
class SimState:
    """Device-side per-peer protocol state (a jax pytree)."""

    mesh_mask: jnp.ndarray      # (N, C) bool — GossipSub mesh ⊆ connections
    fanout_mask: jnp.ndarray    # (N, C) bool — fanout set for unsubscribed publishers
    fanout_expire: jnp.ndarray  # (N,) float32 ms — when each fanout set expires
    #                             (last fanout publish + fanout_ttl_ms; 0 = none)
    backoff_until: jnp.ndarray  # (N, C) float32 ms — PRUNE backoff per directed edge
    fmd: jnp.ndarray            # (N, C) float32 — firstMessageDeliveries counter
    slow_penalty: jnp.ndarray   # (N, C) float32 — slowPeerPenalty COUNTER
    #                             (non-negative; weighted only in score())
    alive: jnp.ndarray          # (N,) bool — churn mask
    subscribed: jnp.ndarray     # (N,) bool — topic membership
    hb_phase: jnp.ndarray       # (N,) float32 ms — per-peer heartbeat phase.
    #                             Nodes start at different wall times, so ticks
    #                             are unaligned; the phase is a property of the
    #                             NODE (drawn once per run), not of a message —
    #                             gossip-arrival timing is consistent across
    #                             messages the way a real node's timer is.
    uplink_free_ms: jnp.ndarray  # (N,) float32 ms — absolute time each peer's
    #                             uplink drains. The reference's per-connection
    #                             queues serialize ALL in-flight traffic
    #                             (main.nim:264-299): a second message published
    #                             while the first is still forwarding queues
    #                             behind it. disseminate() starts each sender at
    #                             max(t_rx + proc, uplink_free) and writes back
    #                             the final occupancy, coupling concurrent
    #                             messages the way shared uplinks do.
    rx_free_ms: jnp.ndarray     # (N,) float32 ms — absolute time each peer's
    #                             DOWNLINK drains. Shadow enforces
    #                             host_bandwidth_down on every host
    #                             (shadow/topogen.py:50-51): every received
    #                             copy — wanted or duplicate — drains the
    #                             receiver's downlink for rx_ms, so a message
    #                             arriving while earlier traffic still drains
    #                             completes no earlier than
    #                             max(wire_arrival, rx_free + rx_ms).
    #                             disseminate() applies that clamp in the
    #                             fixpoint and writes back the exact
    #                             single-server drain time of all copies this
    #                             message delivered (sorted-arrival fold).
    warm_offset_ms: jnp.ndarray  # (N,) float32 ms — arrival OFFSET
    #                             (t_rx - t0) of the most recent fully-
    #                             received message at each peer, INF where
    #                             it never arrived or the carry is invalid.
    #                             disseminate() re-bases these to the next
    #                             publish time as the warm seed of its
    #                             phase-1 relaxation (params.warm_start);
    #                             churn and subscription changes invalidate
    #                             the whole carry to INF (the topology the
    #                             offsets were measured on is gone).
    t_ms: jnp.ndarray           # () float32 — sim clock
    key: jnp.ndarray            # jax PRNG key
    # cumulative observability counters (reference L5). GRAFT/PRUNE are
    # control messages with a sender and a receiver; the Go tracer counts
    # both directions per node (metrics.go:328-336), so all four are (N,)
    grafts: jnp.ndarray         # (N,) int32 GRAFTs sent by each peer
    grafts_rx: jnp.ndarray      # (N,) int32 GRAFTs received
    prunes: jnp.ndarray         # (N,) int32 PRUNEs sent
    prunes_rx: jnp.ndarray      # (N,) int32 PRUNEs received
    bytes_tx: jnp.ndarray       # (N,) float32
    bytes_rx: jnp.ndarray       # (N,) float32
    dup_rx: jnp.ndarray         # (N,) int32
    # per-peer gossip control-message counters, both directions — the
    # shadowlog's per-node ctrl fields are real per-node counters
    # (summary_shadowlog.awk:3-8), so these are (N,)-shaped, not globals
    ihave_tx: jnp.ndarray      # (N,) int32 IHAVE announcements sent
    iwant_tx: jnp.ndarray      # (N,) int32 IWANT requests sent
    ihave_rx: jnp.ndarray      # (N,) int32 IHAVE announcements received
    iwant_rx: jnp.ndarray      # (N,) int32 IWANT requests received
    idontwant_tx: jnp.ndarray  # (N,) int32 IDONTWANTs sent (v1.2: on first
    #                            receipt of a large message, to mesh peers)
    idontwant_rx: jnp.ndarray  # (N,) int32 IDONTWANTs received
    # mesh-repair bookkeeping (ops/repair.py; inert at the repair-off
    # default — the default compiled step neither reads nor writes them)
    px_pool: jnp.ndarray       # (N, PX_POOL_WIDTH) int32 — PX candidate ids
    #                            carried by the most recent PRUNE received;
    #                            -1 = empty slot
    starve_hb: jnp.ndarray     # (N,) int32 — consecutive heartbeats the peer
    #                            spent below d_low (re-dial trigger)
    evictions: jnp.ndarray     # (N,) int32 — score-evictions sent (a subset
    #                            of `prunes`, counted separately)
    px_grafts: jnp.ndarray     # (N,) int32 — mesh edges gained through a PX
    #                            candidate (grafted or dialed+grafted)
    redials: jnp.ndarray       # (N,) int32 — new connections dialed by the
    #                            re-dial controller

    def score(self, params: SimParams) -> jnp.ndarray:
        """Peer score as seen across each directed edge (v1.1 subset:
        P2 firstMessageDeliveries plus the slow-peer penalty counter, each
        scaled by its weight — penalty weights are negative by libp2p
        convention, so the term subtracts)."""
        fmd = jnp.minimum(self.fmd, params.fmd_cap)
        return params.fmd_weight * fmd + params.slow_weight * self.slow_penalty


def init_state(params: SimParams, seed: int = 0) -> SimState:
    import jax

    params.validate()
    n, c = params.n, params.capacity
    key = jax.random.PRNGKey(seed)
    key, k_phase = jax.random.split(key)
    return SimState(
        mesh_mask=jnp.zeros((n, c), dtype=bool),
        fanout_mask=jnp.zeros((n, c), dtype=bool),
        fanout_expire=jnp.zeros((n,), dtype=jnp.float32),
        backoff_until=jnp.zeros((n, c), dtype=jnp.float32),
        fmd=jnp.zeros((n, c), dtype=jnp.float32),
        slow_penalty=jnp.zeros((n, c), dtype=jnp.float32),
        alive=jnp.ones((n,), dtype=bool),
        subscribed=jnp.ones((n,), dtype=bool),
        hb_phase=jax.random.uniform(k_phase, (n,)) * params.heartbeat_ms,
        uplink_free_ms=jnp.zeros((n,), dtype=jnp.float32),
        rx_free_ms=jnp.zeros((n,), dtype=jnp.float32),
        warm_offset_ms=jnp.full((n,), 3.4e38, dtype=jnp.float32),
        t_ms=jnp.asarray(0.0, dtype=jnp.float32),
        key=key,
        grafts=jnp.zeros((n,), dtype=jnp.int32),
        grafts_rx=jnp.zeros((n,), dtype=jnp.int32),
        prunes=jnp.zeros((n,), dtype=jnp.int32),
        prunes_rx=jnp.zeros((n,), dtype=jnp.int32),
        bytes_tx=jnp.zeros((n,), dtype=jnp.float32),
        bytes_rx=jnp.zeros((n,), dtype=jnp.float32),
        dup_rx=jnp.zeros((n,), dtype=jnp.int32),
        ihave_tx=jnp.zeros((n,), dtype=jnp.int32),
        iwant_tx=jnp.zeros((n,), dtype=jnp.int32),
        ihave_rx=jnp.zeros((n,), dtype=jnp.int32),
        iwant_rx=jnp.zeros((n,), dtype=jnp.int32),
        idontwant_tx=jnp.zeros((n,), dtype=jnp.int32),
        idontwant_rx=jnp.zeros((n,), dtype=jnp.int32),
        px_pool=jnp.full((n, PX_POOL_WIDTH), -1, dtype=jnp.int32),
        starve_hb=jnp.zeros((n,), dtype=jnp.int32),
        evictions=jnp.zeros((n,), dtype=jnp.int32),
        px_grafts=jnp.zeros((n,), dtype=jnp.int32),
        redials=jnp.zeros((n,), dtype=jnp.int32),
    )


# The mesh-repair leaves ride SimState so repair-armed traces can carry
# them, but the default (repair-off) compiled step neither reads nor
# writes any of them — they are pure passthrough at every jit boundary
# and dead weight in every scan carry. strip_repair/restore_repair excise
# them HOST-SIDE around the public entrypoints when repair_inert(params):
# a None field is an empty pytree subtree, so the stripped state traces
# through the same code with 5 fewer carry/output buffers (the r05 BENCH
# regression was exactly these buffers riding the publish/heartbeat jits).
REPAIR_LEAVES = ("px_pool", "starve_hb", "evictions", "px_grafts", "redials")


def repair_inert(params: SimParams) -> bool:
    """True iff no compiled path can read or write the repair leaves —
    eviction, PX-on-PRUNE, and re-dial are all off (they gate every repair
    branch behind Python-static `if params.<knob>:` conds)."""
    return not (params.evict or params.px or params.redial)


def strip_repair(state: SimState):
    """(state without repair leaves, saved dict to restore them later)."""
    saved = {k: getattr(state, k) for k in REPAIR_LEAVES}
    return state.replace(**{k: None for k in REPAIR_LEAVES}), saved


def restore_repair(state: SimState, saved: dict) -> SimState:
    """Reattach the leaves strip_repair removed (they were untouched by
    construction — no inert trace references them)."""
    return state.replace(**saved)


# Per-attacker controller leaves for the ADAPTIVE adversary (ops/adversary.py
# AdaptivePolicy). These are the strip_repair discipline taken to its limit:
# instead of riding SimState and being excised host-side when inert, the
# controller is a SEPARATE pytree threaded through the armed scan carry
# (run_adaptive_heartbeats / run_adaptive_recovery_heartbeats) and never
# materialized at all on the disabled path — the delegating wrappers call the
# base runners with the exact argument list, so the default trace cannot grow
# a dead carry leaf by construction (the r05 regression class).
ADAPTIVE_LEAVES = ("viol_est", "regrafts", "px_injected", "throttled_hb")


@struct.dataclass
class AdaptiveCtrl:
    """On-device adaptive-attacker controller state, (N,) per peer (honest
    rows stay zero). `viol_est` is the attacker's own running estimate of
    the worst honest-side slow_penalty counter any of its edges carries —
    updated from its OWN tx view each round (backoff is symmetric on both
    endpoints of an edge; the attacker's mesh bit over-approximates the
    honest one, so the estimate is conservative: est >= max_j counter_j and
    the duty cycle never overshoots the graylist floor). The other leaves
    are attacker-side telemetry counters (ops/telemetry.py channels)."""

    viol_est: jnp.ndarray      # (N,) f32: self-estimated violation counter
    regrafts: jnp.ndarray      # (N,) i32: backoff-expiry re-graft attempts
    px_injected: jnp.ndarray   # (N,) i32: sybil ids planted in px_pool rows
    throttled_hb: jnp.ndarray  # (N,) i32: rounds spent duty-cycled OFF


def init_adaptive_ctrl(n: int) -> AdaptiveCtrl:
    """Zeroed controller carry for a fresh trial window."""
    return AdaptiveCtrl(
        viol_est=jnp.zeros((n,), dtype=jnp.float32),
        regrafts=jnp.zeros((n,), dtype=jnp.int32),
        px_injected=jnp.zeros((n,), dtype=jnp.int32),
        throttled_hb=jnp.zeros((n,), dtype=jnp.int32),
    )


def graph_arrays(graph) -> dict:
    """Move a ConnGraph's arrays to device once (jnp constants per epoch)."""
    return {
        "conns": jnp.asarray(graph.conns),
        "rev": jnp.asarray(graph.rev),
        "out_mask": jnp.asarray(graph.out_mask),
    }


def topo_arrays(topology, payload_bytes: int) -> dict:
    return {
        "stage": jnp.asarray(topology.stage_of_peer),
        "lat_ms": jnp.asarray(topology.latency_ms),
        "tx_ms": jnp.asarray(
            topology.tx_ms_per_peer(payload_bytes).astype(np.float32)
        ),
    }
