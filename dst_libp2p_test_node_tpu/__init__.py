"""dst-libp2p-test-node-tpu: a TPU-native DST (distributed systems testing) framework.

Re-implements the capabilities of vacp2p/dst-libp2p-test-node — a libp2p
GossipSub / Kademlia / connection-manager / service-discovery test harness
driven by the Shadow network simulator — as a single JAX program:

- every simulated peer is a row of peer-major state arrays (the reference
  spawns one OS process per peer: /root/reference/shadow/topogen.py:102-122);
- the static connection graph is a fixed-capacity padded neighbor list and
  the GossipSub mesh is a boolean mask over those edges;
- heartbeat mesh maintenance (graft/prune/score-decay) is a `lax.scan` step;
- message dissemination is an earliest-arrival-time min-relaxation fixpoint
  (scatter-min over mesh edges with uplink serialization and per-stage link
  latency) instead of Shadow's per-packet discrete event queue;
- peers shard across TPU chips via `jax.sharding.Mesh` + `shard_map`; cross
  shard mesh edges resolve with XLA collectives over ICI.

The *surfaces* of the reference are preserved exactly: the env-var config
(PEERS/CONNECTTO/FRAGMENTS/MUXER/GOSSIPSUB_*...), the topogen CLI and its
GML + shadow.yaml outputs, the HTTP /publish control endpoint, the
Prometheus metric names, and the `"<msgId> milliseconds: <ms>"` stdout line
format consumed by the reference's awk summaries.
"""

__version__ = "0.1.0"
