"""Engine 2 — repo-specific AST lint over the jitted hot paths.

Static source analysis, no imports of the linted modules: every rule works
on the parse tree alone, so the linter runs in milliseconds and is safe on
files whose import would cost a device or a trace.

The core is a light taint analysis per *traced scope* (a function wrapped in
``jax.jit`` / ``partial(jax.jit, static_argnames=...)``, plus every function
nested inside one — nested defs are the scan/while/cond bodies and fragment
lambdas, which receive tracers). Parameters not named in ``static_argnames``
are tainted; taint propagates through assignment, arithmetic, calls and
subscripts, and is *neutralized* by the aval-reading attributes
(``.shape``/``.ndim``/``.dtype``/``.size``) and by ``len()``/``isinstance()``
— those yield Python values under tracing, so branching on them is fine.

Inner-function parameters are resolved by CALL-SITE propagation, not blanket
tainting: the linter runs optimistic collect passes to a fixpoint (a param
is tainted only if some call site actually passes it a tainted value, or
the function is passed as a value to ``lax.scan``/``while_loop``/``cond``/
``vmap`` — whose calls supply tracers), then a final report pass. This is
what lets `phases_fast(f, t, warm)`-style static mode flags thread through
helpers without false `if warm:` findings.

Rules (ids in analysis/report.py):
  GA-A001  np.*/math.* applied to a tainted value (host math on a tracer)
  GA-A002  float()/int()/bool() applied to a tainted value (host coercion —
           a TracerBoolConversionError at trace time, or worse, a silent
           constant if the value was accidentally concrete)
  GA-A003  `if`/`while`/ternary whose test is tainted (Python control flow
           on a tracer; the vmapped form silently executes both branches)
  GA-A004  `.item()`/`.block_until_ready()`/`jax.device_get` on a tainted
           value inside a traced scope (host sync under trace)
  GA-A005  json.dump/json.dumps without allow_nan=False and without routing
           through runtime.summarize.sanitize_nonfinite() — non-finite
           floats would poison the strict-JSON artifact chain. Applies to
           whole files, not just traced scopes.

A line ending in ``# graft-audit: ok`` waives any rule on that line.
"""

from __future__ import annotations

import ast
import os

from .report import Violation, suppressed_lines

# attributes whose read yields static Python data even on a tracer
_NEUTRAL_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
# calls that return static Python data regardless of argument taint
_NEUTRAL_CALLS = {"len", "isinstance", "type", "hasattr", "callable", "id",
                  "repr", "str", "format"}
_COERCIONS = {"float", "int", "bool", "complex"}
_HOST_MATH_MODULES = {"np", "numpy", "math"}
_HOST_SYNC_ATTRS = {"item", "block_until_ready"}
_MAX_FIXPOINT_PASSES = 10


def _is_jax_jit(node: ast.expr) -> bool:
    """jax.jit / jit as a bare decorator or partial() first argument."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id in ("jax", "pjit"))


def _static_argnames_from_call(call: ast.Call) -> set[str]:
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return names


def _traced_decoration(fn: ast.FunctionDef) -> set[str] | None:
    """None if not jit-decorated, else the set of static argument names."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return set()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return _static_argnames_from_call(dec)
            # partial(jax.jit, static_argnames=(...)) / functools.partial
            f = dec.func
            is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
                isinstance(f, ast.Attribute) and f.attr == "partial")
            if is_partial and dec.args and _is_jax_jit(dec.args[0]):
                return _static_argnames_from_call(dec)
    return None


def _param_names(fn) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _ScopeLinter:
    """Fixpoint taint walk over one traced scope and its nested functions.

    Collect passes (report=False) only accumulate per-parameter taint for
    inner defs from their call sites; the final report pass emits
    violations using the converged parameter taint.
    """

    def __init__(self, path: str, suppressed: set[int],
                 violations: list[Violation]):
        self.path = path
        self.suppressed = suppressed
        self.violations = violations
        # (id(FunctionDef), param name) -> tainted at some call site
        self.param_taint: dict[tuple[int, str], bool] = {}
        # FunctionDef ids passed as values (loop/branch bodies): all params
        # receive tracers
        self.forced: set[int] = set()
        self.report = False
        self.changed = False

    def lint_scope(self, fn: ast.FunctionDef, static: set[str]) -> None:
        for _ in range(_MAX_FIXPOINT_PASSES):
            self.changed = False
            self.report = False
            self._run(fn, static)
            if not self.changed:
                break
        self.report = True
        self._run(fn, static)

    def _run(self, fn: ast.FunctionDef, static: set[str]) -> None:
        taint = set(_param_names(fn)) - static
        self._lint_function_body(fn, taint, static, {})

    # ---------------------------------------------------------------- taint

    def tainted(self, node: ast.expr, taint: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in taint
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _NEUTRAL_ATTRS:
                return False
            return self.tainted(node.value, taint)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a static structural check
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.tainted(node.left, taint)
                    or any(self.tainted(c, taint) for c in node.comparators))
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in _NEUTRAL_CALLS:
                return False
            parts = [] if isinstance(f, ast.Name) else [f]
            parts += list(node.args)
            parts += [kw.value for kw in node.keywords]
            return any(self.tainted(p, taint) for p in parts)
        if isinstance(node, ast.Lambda):
            return False  # a function value; its body is traced on purpose
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehension over tracers: taint if any free Name is tainted
            return any(isinstance(n, ast.Name) and n.id in taint
                       for n in ast.walk(node))
        # generic: any tainted child expression taints the parent
        return any(self.tainted(c, taint)
                   for c in ast.iter_child_nodes(node)
                   if isinstance(c, ast.expr))

    # ------------------------------------------------------------ reporting

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        if not self.report:
            return
        line = getattr(node, "lineno", 0)
        if line in self.suppressed:
            return
        self.violations.append(
            Violation(rule=rule, file=self.path, line=line, message=message))

    # -------------------------------------------------- inner-def resolution

    def _record_param(self, target: ast.FunctionDef, name: str,
                      is_tainted: bool) -> None:
        key = (id(target), name)
        prev = self.param_taint.get(key, False)
        if is_tainted and not prev:
            self.param_taint[key] = True
            self.changed = True
        elif key not in self.param_taint:
            self.param_taint[key] = prev

    def _force(self, target: ast.FunctionDef) -> None:
        if id(target) not in self.forced:
            self.forced.add(id(target))
            self.changed = True

    def _inner_taint(self, fn: ast.FunctionDef, closure_taint: set[str],
                     env: dict) -> set[str]:
        params = _param_names(fn)
        if id(fn) in self.forced:
            tainted_params = set(params)
        else:
            tainted_params = {p for p in params
                              if self.param_taint.get((id(fn), p), False)}
        return (closure_taint - set(params)) | tainted_params

    def _lint_function_body(self, fn, taint: set[str], static: set[str],
                            env: dict) -> None:
        env = dict(env)
        # hoist sibling defs first: bodies may forward-reference them
        for stmt in fn.body:
            if isinstance(stmt, ast.FunctionDef):
                env[stmt.name] = stmt
        self._lint_body(fn.body, taint, static, env)

    # ---------------------------------------------------------- statements

    def _lint_body(self, body, taint, static, env) -> None:
        for stmt in body:
            self._lint_stmt(stmt, taint, static, env)

    def _assign_target(self, target: ast.expr, taint: set[str],
                       value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                taint.add(target.id)
            else:
                taint.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, taint, value_tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, taint, value_tainted)

    def _lint_stmt(self, stmt, taint, static, env) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = self._inner_taint(stmt, taint, env)
            self._lint_function_body(stmt, inner, static, env)
            return
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value, taint, env)
            vt = self.tainted(stmt.value, taint)
            for t in stmt.targets:
                self._assign_target(t, taint, vt)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value, taint, env)
                self._assign_target(stmt.target, taint,
                                    self.tainted(stmt.value, taint))
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value, taint, env)
            if self.tainted(stmt.value, taint):
                self._assign_target(stmt.target, taint, True)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, taint, env)
            if self.tainted(stmt.test, taint):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self._flag(
                    "GA-A003", stmt,
                    f"Python `{kind}` on a traced value — use lax.cond/"
                    "jnp.where (a vmapped branch executes both sides)")
            self._lint_body(stmt.body, taint, static, env)
            self._lint_body(stmt.orelse, taint, static, env)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter, taint, env)
            self._assign_target(stmt.target, taint,
                                self.tainted(stmt.iter, taint))
            self._lint_body(stmt.body, taint, static, env)
            self._lint_body(stmt.orelse, taint, static, env)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, taint, env)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, taint, env)
            return
        if isinstance(stmt, (ast.With, ast.Try)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    self._lint_stmt(child, taint, static, env)
                elif isinstance(child, ast.expr):
                    self._scan_expr(child, taint, env)
            return
        # default: scan embedded expressions for call-site rules
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, taint, env)

    # -------------------------------------------------------- expressions

    def _scan_expr(self, expr: ast.expr, taint: set[str], env: dict) -> None:
        if isinstance(expr, ast.Call):
            self._check_call(expr, taint, env)
            for part in list(expr.args) + [kw.value for kw in expr.keywords]:
                self._scan_expr(part, taint, env)
            if not isinstance(expr.func, ast.Name):
                self._scan_expr(expr.func, taint, env)
            return
        if isinstance(expr, ast.IfExp):
            if self.tainted(expr.test, taint):
                self._flag(
                    "GA-A003", expr,
                    "ternary on a traced value — use jnp.where/lax.cond")
            for part in (expr.test, expr.body, expr.orelse):
                self._scan_expr(part, taint, env)
            return
        if isinstance(expr, ast.Lambda):
            # lambdas ARE the scan/cond bodies: their params are tracers
            inner = (set(taint) - set(_param_names(expr))) \
                | set(_param_names(expr))
            self._scan_expr(expr.body, inner, env)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._scan_expr(child, taint, env)

    def _resolve(self, node: ast.expr, env: dict):
        if isinstance(node, ast.Name):
            target = env.get(node.id)
            if isinstance(target, ast.FunctionDef):
                return target
        return None

    def _check_call(self, call: ast.Call, taint: set[str], env: dict) -> None:
        f = call.func
        argish = list(call.args) + [kw.value for kw in call.keywords]
        # inner functions passed as VALUES (scan/while/cond bodies, vmap
        # operands, cond branches): all their params receive tracers.
        # Names in callee position of a nested call are direct calls, not
        # value references — those are handled by per-param recording.
        for a in argish:
            callee_ids = {id(c.func) for c in ast.walk(a)
                          if isinstance(c, ast.Call)
                          and isinstance(c.func, ast.Name)}
            for n in ast.walk(a):
                if id(n) in callee_ids:
                    continue
                target = self._resolve(n, env)
                if target is not None:
                    self._force(target)
        # direct calls to inner functions: record per-parameter taint
        target = self._resolve(f, env)
        if target is not None:
            names = _param_names(target)
            for i, a in enumerate(call.args):
                if isinstance(a, ast.Starred):
                    self._force(target)
                    break
                if i < len(names):
                    self._record_param(target, names[i],
                                      self.tainted(a, taint))
            for kw in call.keywords:
                if kw.arg is None:
                    self._force(target)
                elif kw.arg in names:
                    self._record_param(target, kw.arg,
                                       self.tainted(kw.value, taint))
        any_tainted_arg = any(self.tainted(a, taint) for a in argish)
        if isinstance(f, ast.Name) and f.id in _COERCIONS and any_tainted_arg:
            self._flag(
                "GA-A002", call,
                f"{f.id}() on a traced value forces a host round-trip "
                "(TracerBoolConversionError under jit)")
        if isinstance(f, ast.Attribute):
            base = f.value
            if (isinstance(base, ast.Name)
                    and base.id in _HOST_MATH_MODULES and any_tainted_arg):
                self._flag(
                    "GA-A001", call,
                    f"{base.id}.{f.attr}() on a traced value — use the "
                    "jnp./lax. equivalent (host math breaks the trace)")
            if f.attr in _HOST_SYNC_ATTRS and self.tainted(base, taint):
                self._flag(
                    "GA-A004", call,
                    f".{f.attr}() inside a traced scope synchronizes with "
                    "the host")
            if (f.attr == "device_get" and isinstance(base, ast.Name)
                    and base.id == "jax" and any_tainted_arg):
                self._flag(
                    "GA-A004", call,
                    "jax.device_get() inside a traced scope synchronizes "
                    "with the host")


def _check_json_calls(tree: ast.Module, path: str, suppressed: set[int],
                      violations: list[Violation]) -> None:
    """GA-A005 over the whole file (artifact writers live outside jit)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("dump", "dumps")
                and isinstance(f.value, ast.Name) and f.value.id == "json"):
            continue
        ok = any(
            kw.arg == "allow_nan"
            and isinstance(kw.value, ast.Constant) and kw.value.value is False
            for kw in node.keywords)
        if not ok and node.args:
            first = node.args[0]
            if (isinstance(first, ast.Call)
                    and ((isinstance(first.func, ast.Name)
                          and first.func.id == "sanitize_nonfinite")
                         or (isinstance(first.func, ast.Attribute)
                             and first.func.attr == "sanitize_nonfinite"))):
                ok = True
        if not ok and node.lineno not in suppressed:
            violations.append(Violation(
                rule="GA-A005", file=path, line=node.lineno,
                message=f"json.{f.attr}() without allow_nan=False — wrap the "
                        "payload in runtime.summarize.sanitize_nonfinite() "
                        "or pass allow_nan=False (strict-JSON artifacts)"))


def lint_source(source: str, path: str) -> list[Violation]:
    """Lint one file's source text; `path` is used only for reporting."""
    violations: list[Violation] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(rule="GA-A001", file=path, line=e.lineno or 0,
                          message=f"syntax error: {e.msg}")]
    suppressed = suppressed_lines(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            static = _traced_decoration(node)
            if static is not None:
                linter = _ScopeLinter(path, suppressed, violations)
                linter.lint_scope(node, static)
    _check_json_calls(tree, path, suppressed, violations)
    return violations


def lint_paths(paths: list[str], repo_root: str) -> tuple[list[Violation], int]:
    """Lint every .py file under `paths`; returns (violations, file_count)."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(dirpath, fn)
                             for fn in filenames if fn.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    violations: list[Violation] = []
    for fp in sorted(set(files)):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(fp, repo_root)
        violations.extend(lint_source(source, rel))
    return violations, len(set(files))
