"""Conformance oracle: the spec-differential gate (docs/CONFORMANCE.md).

Drives BOTH sides of the faithfulness claim over the same small adversarial
instance and diffs the full state trajectory field-by-field, every round:

  spec side    ops/spec.py — the pure-numpy transcription of the GossipSub
               v1.1 transition relation (ACL2s formalization,
               arXiv:2311.08859) with the engine's PRNG stream as the
               selection oracle, so the relation becomes a function.
  sim side     the compiled engine — one jitted `differential_round`
               (heartbeat_step -> adversary_round) per heartbeat, the same
               step composition every attack runner scans over, registered
               as an EntrypointContract so the jaxpr gate audits the exact
               program the differential exercises.

The harness closes the loop twice: after the per-round walk it re-runs the
REAL scan runner (run_attacked_heartbeats / run_adaptive_heartbeats /
run_faulted_heartbeats) from the same initial state and demands the final
states agree bit-for-bit with the per-round walk ("runner coherence") — so
a scan-body refactor cannot drift from the audited per-round composition
without tripping the gate.

Divergence policy: every field mismatch becomes a record; records are
classified against the waiver table in docs/CONFORMANCE.md (first
fnmatch(scenario) & fnmatch(field) row wins) as `documented_choice`, or
`sim_bug` when no row matches. Any sim_bug fails the certificate — an
unwaivered divergence is a hard failure, never a warning. Certificates are
strict JSON (json.dump(allow_nan=False) over sanitize_nonfinite output):
a NaN anywhere in the artifact is itself a bug.

Comparison discipline: bool/int leaves must match EXACTLY; float leaves get
np.isclose(rtol=1e-5, atol=1e-4) — spec.py keeps every host op in float32
with the engine's op order, so observed deltas are 0 ulp on XLA:CPU and the
tolerance is headroom for fused-multiply-add reassociation on other
backends, not a semantic allowance.
"""

from __future__ import annotations

import fnmatch
import json
from functools import partial
from pathlib import Path

import numpy as np

__all__ = [
    "FLOAT_RTOL", "FLOAT_ATOL", "ARMED", "MUTANTS",
    "differential_round", "differential_adaptive_round",
    "run_scenario_differential", "run_adaptive_differential",
    "run_faults_differential", "run_churn_differential",
    "run_og_differential",
    "cross_fragment_check", "load_waivers", "classify",
    "conformance_certificate", "certificate_entry", "write_certificate",
]

FLOAT_RTOL = 1e-5
FLOAT_ATOL = 1e-4

# the armed-defense config every differential runs under (the onset-fixture
# arming of tests/test_adversary.py): thresholds live, so the score-gated
# guards (graft acceptance, graylist refusal) are real branches on both sides
ARMED = dict(slow_weight=-10.0, slow_decay=0.9, gossip_threshold=-10.0,
             publish_threshold=-20.0, graylist_threshold=-50.0)

_DEFAULT_WAIVERS = Path(__file__).resolve().parents[2] / "docs" / "CONFORMANCE.md"


# ---------------------------------------------------------------------------
# compiled side: the audited per-round unit


def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def _make_rounds():
    import jax

    from ..ops.adversary import adaptive_round, adversary_round
    from ..ops.heartbeat import heartbeat_step

    @partial(jax.jit, static_argnames=("params", "adv"))
    def differential_round(state, conns, rev, out_mask, attacker, params,
                           adv, hb_idx, edge_ok=None):
        """One conformance heartbeat: the exact [heartbeat_step ->
        adversary_round] composition every attack runner scans over, jitted
        as a standalone unit so (a) the differential exercises the compiled
        program, not op-by-op eager dispatch, and (b) the jaxpr gate can
        audit it (registry: conformance/differential_round)."""
        state = heartbeat_step(state, conns, rev, out_mask, params,
                               edge_ok=edge_ok)
        state, _obs = adversary_round(state, conns, rev, attacker, params,
                                      adv, edge_ok=edge_ok, hb_idx=hb_idx)
        return state

    @partial(jax.jit, static_argnames=("params", "adv"))
    def differential_adaptive_round(state, ctrl, conns, rev, out_mask,
                                    attacker, params, adv, hb_idx):
        state = heartbeat_step(state, conns, rev, out_mask, params)
        (state, ctrl), _obs = adaptive_round(state, ctrl, conns, rev,
                                             attacker, params, adv,
                                             hb_idx=hb_idx)
        return state, ctrl

    return differential_round, differential_adaptive_round


_ROUNDS = None


def _rounds():
    global _ROUNDS
    if _ROUNDS is None:
        _ROUNDS = _make_rounds()
    return _ROUNDS


def differential_round(*args, **kwargs):
    return _rounds()[0](*args, **kwargs)


def differential_adaptive_round(*args, **kwargs):
    return _rounds()[1](*args, **kwargs)


# ---------------------------------------------------------------------------
# trajectory diffing


def _diff_field(field, sim, spec, scenario, seed, step):
    """One field comparison -> a divergence record, or None on agreement."""
    sim = np.asarray(sim)
    spec = np.asarray(spec)
    if sim.dtype == bool or np.issubdtype(sim.dtype, np.integer):
        bad = sim != spec
        max_err = float(np.abs(sim.astype(np.int64)
                               - spec.astype(np.int64)).max()) if bad.any() else 0.0
    else:
        bad = ~np.isclose(sim, spec, rtol=FLOAT_RTOL, atol=FLOAT_ATOL)
        max_err = float(np.abs(sim - spec)[bad].max()) if bad.any() else 0.0
    if not bad.any():
        return None
    idx = tuple(int(v) for v in np.argwhere(bad)[0])
    return {
        "scenario": scenario, "seed": int(seed), "step": int(step),
        "field": field, "count": int(bad.sum()), "max_abs_err": max_err,
        "sim_sample": _scalar(sim[idx] if sim.shape else sim),
        "spec_sample": _scalar(spec[idx] if spec.shape else spec),
    }


def _scalar(v):
    v = np.asarray(v)
    if v.dtype == bool:
        return bool(v)
    if np.issubdtype(v.dtype, np.integer):
        return int(v)
    return float(v)


def _diff_states(sim_state, spec_st, scenario, seed, step, prefix=""):
    from ..ops.spec import SPEC_FIELDS

    divs = []
    for f in SPEC_FIELDS:
        sim = getattr(sim_state, f)
        if sim is None or spec_st.get(f) is None:
            continue
        d = _diff_field(prefix + f, sim, spec_st[f], scenario, seed, step)
        if d is not None:
            divs.append(d)
    return divs


# a mutant trajectory diverges every subsequent round; cap the walk so a
# deliberately broken step yields a bounded record set, not steps*fields
_MAX_DIV_STEPS = 3


# ---------------------------------------------------------------------------
# scenario differentials


def _fixture(scenario, n, connect_to, seed, params=None, adv=None,
             warm_steps=4, fraction=0.2, publisher=3):
    """Shared trial setup: graph, armed params, warm (or cold) state, cohort.
    Mirrors the campaign's trial sequencing — warmup runs BEFORE the window
    except for cold_boot_join (mesh formation under fire), and the eclipse
    closes (eclipse_setup) after warmup, before round 0."""
    _, jnp = _jax()
    from ..ops.adversary import AdversaryParams, attacker_cohort, eclipse_setup
    from ..ops.graph import build_connection_graph
    from ..ops.heartbeat import run_heartbeats
    from ..ops.state import SimParams, graph_arrays, init_state

    g = build_connection_graph(n, connect_to, seed=seed)
    if params is None:
        params = SimParams(n=n, capacity=g.capacity, **ARMED)
    if adv is None:
        adv = AdversaryParams(scenario=scenario)
    a = graph_arrays(g)
    state = init_state(params, seed=seed)
    if warm_steps and not adv.cold_boot:
        state = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                               params, warm_steps)
    att_np = attacker_cohort(n, fraction, seed=seed + 1,
                             conns=np.asarray(g.conns), publisher=publisher,
                             eclipse=adv.eclipse)
    att = jnp.asarray(att_np)
    if adv.eclipse:
        state = eclipse_setup(state, a["conns"], att, publisher)
    hosts = dict(conns=np.asarray(g.conns), rev=np.asarray(g.rev),
                 out_mask=np.asarray(g.out_mask), att=att_np)
    return g, params, adv, a, state, att, hosts


def run_scenario_differential(scenario, n=48, connect_to=8, seed=0, steps=8,
                              warm_steps=4, params=None, adv=None,
                              mutate=None, fraction=0.2):
    """Walk `steps` heartbeats of one attack scenario through both models
    and return the divergence records (empty == conformant).

    `mutate(pre_state, post_state) -> state` is the fault-injection hook:
    applied to the SIM side after each round, it models a spec violation in
    the compiled step (tests use it to prove the differential actually
    discriminates — see MUTANTS)."""
    jax, jnp = _jax()
    from ..ops.adversary import censorship_penalty_update, run_attacked_heartbeats
    from ..ops.spec import (host_state, spec_adversary_round,
                            spec_censorship_penalty, spec_heartbeat)

    g, params, adv, a, state, att, hosts = _fixture(
        scenario, n, connect_to, seed, params, adv, warm_steps, fraction)
    state0 = state
    st = host_state(state)
    received = ~hosts["att"]

    divs = []
    div_steps = 0
    for i in range(steps):
        pre = state
        state = differential_round(state, a["conns"], a["rev"],
                                   a["out_mask"], att, params, adv,
                                   jnp.int32(i))
        if mutate is not None:
            state = mutate(pre, state)
        st = spec_heartbeat(st, hosts["conns"], hosts["rev"],
                            hosts["out_mask"], params)
        st = spec_adversary_round(st, hosts["conns"], hosts["rev"],
                                  hosts["att"], params, adv, i)
        if scenario == "censorship":
            # the censorship dynamics live in the per-publish penalty
            # update, not adversary_round; one update per heartbeat is the
            # onset-test convention (tests/test_adversary.py)
            state = censorship_penalty_update(
                state, a["conns"], a["rev"], att, jnp.asarray(received),
                params, adv)
            st = spec_censorship_penalty(st, hosts["conns"], hosts["rev"],
                                         hosts["att"], received, params, adv)
        step_divs = _diff_states(state, st, scenario, seed, i)
        if step_divs:
            divs.extend(step_divs)
            div_steps += 1
            if div_steps >= _MAX_DIV_STEPS:
                return divs

    if mutate is None and scenario != "censorship":
        # runner coherence: the scanned runner must reproduce the audited
        # per-round composition bit-for-bit (skipped for censorship, whose
        # per-publish update is campaign-side, outside the runner's scan)
        final, _obs = run_attacked_heartbeats(
            state0, a["conns"], a["rev"], a["out_mask"], att, params, adv,
            steps)
        ref = {f: np.asarray(getattr(state, f))
               for f in _spec_fields() if getattr(state, f) is not None}
        divs.extend(_diff_states(final, ref, scenario, seed, steps,
                                 prefix="runner_coherence:"))
    return divs


def _spec_fields():
    from ..ops.spec import SPEC_FIELDS
    return SPEC_FIELDS


def run_adaptive_differential(scenario="sybil_graft_flood", n=48,
                              connect_to=8, seed=0, steps=8, warm_steps=4,
                              fraction=0.2):
    """The AdaptivePolicy differential: heartbeat -> adaptive_round with the
    controller carry compared alongside the state (ctrl.* fields). Repair
    leaves are LIVE (evict+px armed) so the PX poisoner writes real px_pool
    rows on both sides — the stripped path would compile the poison out."""
    jax, jnp = _jax()
    from ..ops.adversary import (AdaptivePolicy, AdversaryParams,
                                 run_adaptive_heartbeats)
    from ..ops.spec import host_state, spec_adaptive_round, spec_heartbeat
    from ..ops.state import SimParams, init_adaptive_ctrl

    adv = AdversaryParams(scenario=scenario,
                          adaptive=AdaptivePolicy(enabled=True))
    from ..ops.graph import build_connection_graph
    g = build_connection_graph(n, connect_to, seed=seed)
    params = SimParams(n=n, capacity=g.capacity, evict=True, px=True, **ARMED)
    g, params, adv, a, state, att, hosts = _fixture(
        scenario, n, connect_to, seed, params, adv, warm_steps, fraction)
    state0 = state
    ctrl = init_adaptive_ctrl(n)
    st = host_state(state)
    sctrl = dict(viol_est=np.zeros(n, np.float32),
                 regrafts=np.zeros(n, np.int32),
                 px_injected=np.zeros(n, np.int32),
                 throttled_hb=np.zeros(n, np.int32))

    divs = []
    div_steps = 0
    for i in range(steps):
        state, ctrl = differential_adaptive_round(
            state, ctrl, a["conns"], a["rev"], a["out_mask"], att, params,
            adv, jnp.int32(i))
        st = spec_heartbeat(st, hosts["conns"], hosts["rev"],
                            hosts["out_mask"], params)
        st, sctrl = spec_adaptive_round(st, sctrl, hosts["conns"],
                                        hosts["rev"], hosts["att"], params,
                                        adv, i)
        step_divs = _diff_states(state, st, "adaptive", seed, i)
        for f in ("viol_est", "regrafts", "px_injected", "throttled_hb"):
            d = _diff_field("ctrl." + f, getattr(ctrl, f), sctrl[f],
                            "adaptive", seed, i)
            if d is not None:
                step_divs.append(d)
        if step_divs:
            divs.extend(step_divs)
            div_steps += 1
            if div_steps >= _MAX_DIV_STEPS:
                return divs

    (final, fctrl), _obs = run_adaptive_heartbeats(
        state0, a["conns"], a["rev"], a["out_mask"], att, params, adv,
        steps, ctrl=init_adaptive_ctrl(n))
    ref = {f: np.asarray(getattr(state, f)) for f in _spec_fields()}
    divs.extend(_diff_states(final, ref, "adaptive", seed, steps,
                             prefix="runner_coherence:"))
    for f in ("viol_est", "regrafts", "px_injected", "throttled_hb"):
        d = _diff_field("runner_coherence:ctrl." + f, getattr(fctrl, f),
                        np.asarray(getattr(ctrl, f)), "adaptive", seed, steps)
        if d is not None:
            divs.append(d)
    return divs


def run_faults_differential(n=48, connect_to=8, seed=0, steps=8,
                            warm_steps=4, fraction=0.2):
    """One fault family through the oracle: crash/restart + partition
    freeze/thaw + latency spike layered over a sybil graft-flood. The sim
    side is ONE run_faulted_heartbeats call (the real scan, fault conds
    compiled in); the spec side replays the documented body order
    (crash conds -> freeze/thaw + edge_ok -> heartbeat -> adversary ->
    spike) per round, and the FINAL states must agree."""
    jax, jnp = _jax()
    from ..ops.faults import FaultParams, fault_masks, run_faulted_heartbeats
    from ..ops.spec import (host_state, spec_adversary_round, spec_freeze,
                            spec_go_dark, spec_heartbeat,
                            spec_partition_edge_mask, spec_restart,
                            spec_spike, spec_thaw)

    faults = FaultParams(crash_frac=0.2, crash_window=(1, 3),
                         partition_frac=0.3, partition_window=(2, 5),
                         spike_frac=0.2, spike_window=(0, 4), spike_ms=250.0)
    assert steps > faults.partition_window[1], "thaw must land in-window"
    g, params, adv, a, state, att, hosts = _fixture(
        "sybil_graft_flood", n, connect_to, seed, None, None, warm_steps,
        fraction)
    masks = fault_masks(n, faults, seed=seed + 2, publisher=3)
    crash, side, spike = masks["crash"], masks["side"], masks["spike"]

    st = host_state(state)
    cross = spec_partition_edge_mask(side, hosts["conns"])
    frozen = np.zeros_like(cross)
    cs, ce = faults.crash_window
    ps, pe = faults.partition_window
    ss, se = faults.spike_window
    for hb in range(steps):
        if hb == cs:
            st = spec_go_dark(st, crash)
        if hb == ce:
            st = spec_restart(st, crash, hosts["conns"], hosts["rev"], params)
        if hb == ps:
            st, frozen = spec_freeze(st, cross)
        if hb == pe:
            st, frozen = spec_thaw(st, frozen, hosts["conns"])
        edge_ok = ~cross if ps <= hb < pe else np.ones_like(cross)
        st = spec_heartbeat(st, hosts["conns"], hosts["rev"],
                            hosts["out_mask"], params, edge_ok=edge_ok)
        st = spec_adversary_round(st, hosts["conns"], hosts["rev"],
                                  hosts["att"], params, adv, hb,
                                  edge_ok=edge_ok)
        if ss <= hb < se:
            st = spec_spike(st, spike, faults.spike_ms)

    final, _obs = run_faulted_heartbeats(
        state, a["conns"], a["rev"], a["out_mask"], att, params, adv,
        faults, jnp.asarray(crash), jnp.asarray(side), jnp.asarray(spike),
        steps)
    return _diff_states(final, st, "faults", seed, steps)


def run_churn_differential(n=48, connect_to=8, seed=0, steps=8,
                           warm_steps=4):
    """Benign churn differential: a zero-attacker walk with churn armed, so
    the k_churn_d/k_churn_u PRNG draws and the liveness-driven validity
    algebra are covered (an all-False cohort makes adversary_round the
    identity on state)."""
    from ..ops.state import SimParams

    params = None

    def build_params(g):
        return SimParams(n=n, capacity=g.capacity, churn_down_per_hb=0.02,
                         churn_up_per_hb=0.05, **ARMED)

    from ..ops.graph import build_connection_graph
    g = build_connection_graph(n, connect_to, seed=seed)
    params = build_params(g)
    return run_scenario_differential(
        "sybil_graft_flood", n=n, connect_to=connect_to, seed=seed,
        steps=steps, warm_steps=warm_steps, params=params, fraction=0.0)


def run_og_differential(n=48, connect_to=8, seed=0, steps=8, warm_steps=4,
                        fraction=0.35, og_threshold=-1.0, tie_highest=False):
    """Opportunistic-grafting differential (the registry-refactor gate's
    spec-depth rung): og ARMED over a sybil graft flood whose violation
    penalties drag the honest mesh median under `og_threshold`, so the
    v1.1 og rule — median probe, strict-above-median eligibility, top-2 by
    score — fires on both sides and the walk pins the engine to the
    spec's tie policy (ops/spec.opportunistic_graft_candidates: lowest
    neighbor slot among equal scores, the executable resolution of the
    ACL2s nondeterministic choice).

    The fixture is self-checking: it RAISES unless (a) the og branch
    actually fired during the walk and (b) at least one fired round held
    a DECISIVE tie (the lowest-slot and highest-slot resolutions select
    different edges) — otherwise a bitwise-clean differential would say
    nothing about the tie policy. `tie_highest=True` runs the spec side
    under the other admissible resolution; the divergence it must produce
    is the discrimination proof (tests/test_conformance.py)."""
    jax, jnp = _jax()
    from ..ops.adversary import run_attacked_heartbeats
    from ..ops.graph import build_connection_graph
    from ..ops.spec import (_validity, host_state,
                            opportunistic_graft_candidates,
                            spec_adversary_round, spec_heartbeat, spec_score)
    from ..ops.state import SimParams

    g = build_connection_graph(n, connect_to, seed=seed)
    params = SimParams(n=n, capacity=g.capacity,
                       opportunistic_graft_threshold=og_threshold, **ARMED)
    g, params, adv, a, state, att, hosts = _fixture(
        "sybil_graft_flood", n, connect_to, seed, params, None, warm_steps,
        fraction)
    state0 = state
    st = host_state(state)

    divs = []
    div_steps = 0
    fired = False
    decisive = False
    for i in range(steps):
        # fixture-quality probe (advisory, pre-step state): would the og
        # rule fire here, and does the tie policy decide the selection?
        valid = _validity(st, hosts["conns"], hosts["rev"], st["alive"],
                          None)
        scores = spec_score(st, params)
        pmesh = st["mesh_mask"] & valid
        og_lo, _, _ = opportunistic_graft_candidates(
            pmesh, valid, st["backoff_until"], np.float32(st["t_ms"]),
            scores, params)
        og_hi, _, _ = opportunistic_graft_candidates(
            pmesh, valid, st["backoff_until"], np.float32(st["t_ms"]),
            scores, params, highest_slot_ties=True)
        fired = fired or bool(og_lo.any())
        decisive = decisive or bool((og_lo != og_hi).any())

        state = differential_round(state, a["conns"], a["rev"],
                                   a["out_mask"], att, params, adv,
                                   jnp.int32(i))
        st = spec_heartbeat(st, hosts["conns"], hosts["rev"],
                            hosts["out_mask"], params,
                            og_tie_highest=tie_highest)
        st = spec_adversary_round(st, hosts["conns"], hosts["rev"],
                                  hosts["att"], params, adv, i)
        step_divs = _diff_states(state, st, "opportunistic_graft", seed, i)
        if step_divs:
            divs.extend(step_divs)
            div_steps += 1
            if div_steps >= _MAX_DIV_STEPS:
                break
    if not fired:
        raise RuntimeError(
            "og differential fixture never exercised the opportunistic-"
            "grafting branch — raise fraction or og_threshold")
    if not decisive:
        raise RuntimeError(
            "og differential fixture never held a decisive score tie — "
            "the walk cannot pin the tie policy")

    if not tie_highest and div_steps < _MAX_DIV_STEPS:
        # runner coherence, same contract as run_scenario_differential
        final, _obs = run_attacked_heartbeats(
            state0, a["conns"], a["rev"], a["out_mask"], att, params, adv,
            steps)
        ref = {f: np.asarray(getattr(state, f))
               for f in _spec_fields() if getattr(state, f) is not None}
        divs.extend(_diff_states(final, ref, "opportunistic_graft", seed,
                                 steps, prefix="runner_coherence:"))
    return divs


def cross_fragment_check(n=64, connect_to=8, seed=0, fragments=3,
                         payload_bytes=60000, loss=0.25):
    """The `with_gossip AND fragments>1` shape (VERDICT round-5 item 6):
    lossy multi-fragment publish with gossip recovery live. The fragment
    lanes are vmapped — a peer answering IWANTs for fragments f and f+1 of
    ONE message serializes each lane's answers on an independent copy of its
    uplink clock; the cross-lane coupling is deliberately uncoupled
    (ops/disseminate.py). The run is in BOUNDED delivery mode because
    `answer_wait_max_ms` is that mode's per-hop queue witness (exact mode
    repairs within-lane times and reports 0.0 by construction, which says
    nothing about the cross-lane term). When waits fire here, answers
    really queue at this shape, the uncoupling is load-bearing, and the
    record below must carry the documented_choice waiver; if no wait fires
    the shape is pinned green."""
    _, jnp = _jax()
    from ..config.topology import TopoParams, Topology
    from ..ops.disseminate import disseminate
    from ..ops.graph import build_connection_graph
    from ..ops.state import SimParams, graph_arrays, init_state
    from ..ops.heartbeat import run_heartbeats

    g = build_connection_graph(n, connect_to, seed=seed)
    params = SimParams(n=n, capacity=g.capacity, serialize_answers=False,
                       **ARMED)
    a = graph_arrays(g)
    state = init_state(params, seed=seed)
    state = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                           params, 4)
    t = Topology.build(TopoParams(
        network_size=n, anchor_stages=5, min_bandwidth=50, max_bandwidth=150,
        min_latency=40, max_latency=130))
    stage = jnp.asarray(t.stage_of_peer)
    lat = jnp.asarray(t.latency_ms)
    bw = jnp.asarray(t.bw_up_mbit)
    s1 = int(np.asarray(t.stage_of_peer).max()) + 2
    loss_stage = jnp.full((s1, s1), np.float32(loss))
    res, _ = disseminate(state, a["conns"], a["rev"], stage, lat, bw,
                         publisher=3, t0_ms=0.0, params=params,
                         payload_bytes=payload_bytes, fragments=fragments,
                         with_gossip=True, loss_stage=loss_stage)
    wait = float(np.asarray(res.answer_wait_max_ms))
    inter = int(np.asarray(res.answer_interleaved))
    if wait <= 0.0:
        return []
    return [{
        "scenario": "gossip_fragments", "seed": int(seed), "step": -1,
        "field": "cross_fragment_answer_serialization",
        "count": max(inter, 1), "max_abs_err": wait,
        "sim_sample": wait, "spec_sample": 0.0,
    }]


# ---------------------------------------------------------------------------
# mutants: deliberately broken steps the differential must catch


def _drop_prune_backoff(pre, post):
    """Violates the PRUNE backoff rule: the engine 'forgets' to write
    backoff_until, so a pruned edge is immediately re-graftable."""
    return post.replace(backoff_until=pre.backoff_until)


def _drop_violation_penalty(pre, post):
    """Violates the behaviour-penalty rule (and decay): slow_penalty rolls
    back to its pre-round value every heartbeat."""
    return post.replace(slow_penalty=pre.slow_penalty)


MUTANTS = {
    "drop_prune_backoff": _drop_prune_backoff,
    "drop_violation_penalty": _drop_violation_penalty,
}


# ---------------------------------------------------------------------------
# waivers + classification


def load_waivers(path=None):
    """Parse the docs/CONFORMANCE.md waiver table: markdown rows of
    | `key` | scenario-glob | field-glob | rationale |. Returns the rows in
    file order (first match wins)."""
    path = Path(path) if path is not None else _DEFAULT_WAIVERS
    if not path.exists():
        return []
    rows = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        cells = [c.strip().strip("`").strip() for c in line.strip("|").split("|")]
        if len(cells) < 4:
            continue
        if cells[0].lower() in ("key", "waiver key") or set(cells[0]) <= {"-", ":", " "}:
            continue
        rows.append({"key": cells[0], "scenario": cells[1],
                     "field": cells[2], "rationale": cells[3]})
    return rows


def classify(divergences, waivers):
    """Attach classification to each record: the first waiver row whose
    scenario AND field globs both match makes it a documented_choice;
    anything unmatched is a sim_bug."""
    out = []
    for d in divergences:
        d = dict(d)
        waiver = next(
            (w for w in waivers
             if fnmatch.fnmatch(d["scenario"], w["scenario"])
             and fnmatch.fnmatch(d["field"], w["field"])), None)
        if waiver is not None:
            d["classification"] = "documented_choice"
            d["waiver"] = waiver["key"]
        else:
            d["classification"] = "sim_bug"
            d["waiver"] = None
        out.append(d)
    return out


def certificate_entry(scenario, divergences, waivers, **meta):
    divs = classify(divergences, waivers)
    bugs = sum(1 for d in divs if d["classification"] == "sim_bug")
    status = ("fail" if bugs else ("waived" if divs else "pass"))
    return dict(scenario=scenario, status=status, sim_bugs=bugs,
                divergences=divs, **meta)


# ---------------------------------------------------------------------------
# parameter-grid fuzzing: random SimParams through the same differential


def sample_sim_params(rng, capacity):
    """One random parameter grid for the differential, as a kwargs dict.

    The degree lattice respects the v1.1 invariants the router assumes:
    0 < d_low <= d <= d_high <= capacity, d_score <= d, and
    d_out < d_low with d_out <= d/2 (the outbound-quota constraints the
    reference enforces at config time). Score knobs stay in the armed
    regime — negative penalty weight, ordered thresholds
    gossip >= publish >= graylist — so every score-gated branch remains a
    live branch on both sides of the differential."""
    d_low = int(rng.integers(1, min(6, capacity) + 1))
    d = int(rng.integers(d_low, min(capacity, d_low + 6) + 1))
    d_high = int(rng.integers(d, capacity + 1))
    d_score = int(rng.integers(1, d + 1))
    d_out = int(rng.integers(1, max(1, min(d_low - 1, d // 2)) + 1))
    d_lazy = int(rng.integers(1, capacity + 1))
    gossip_threshold = round(float(rng.uniform(-20.0, -2.0)), 3)
    publish_threshold = round(
        gossip_threshold - float(rng.uniform(1.0, 20.0)), 3)
    graylist_threshold = round(
        publish_threshold - float(rng.uniform(1.0, 40.0)), 3)
    return dict(
        d=d, d_low=d_low, d_high=d_high, d_score=d_score, d_out=d_out,
        d_lazy=d_lazy,
        gossip_factor=round(float(rng.uniform(0.05, 0.5)), 3),
        slow_weight=round(float(rng.uniform(-20.0, -1.0)), 3),
        slow_decay=round(float(rng.uniform(0.1, 0.95)), 3),
        gossip_threshold=gossip_threshold,
        publish_threshold=publish_threshold,
        graylist_threshold=graylist_threshold,
    )


def run_fuzz_differential(n_samples, n=48, connect_to=8, seed=0, steps=8,
                          warm_steps=4, fuzz_seed=0):
    """`n_samples` random parameter grids through the scenario differential.

    Returns [(entry_name, knobs, divergences)] — one differential instance
    per sample, cycling through the attack canon so every scenario's
    branches meet fuzzed degree bounds / gossip factor / score weights, not
    just the ARMED point the fixed certificate pins. Deterministic in
    fuzz_seed (np.random.default_rng stream; graph/state/cohort reseed from
    `seed` exactly as the fixed entries do). Each distinct grid is a fresh
    jit static arg — expect one compile per sample."""
    from ..ops.adversary import SCENARIOS
    from ..ops.graph import build_connection_graph
    from ..ops.state import SimParams

    rng = np.random.default_rng(fuzz_seed)
    # capacity is a property of the topology, not a fuzzable knob: the
    # fixture will rebuild this exact graph (same n/connect_to/seed)
    g = build_connection_graph(n, connect_to, seed=seed)
    out = []
    for k in range(n_samples):
        knobs = sample_sim_params(rng, g.capacity)
        scenario = SCENARIOS[k % len(SCENARIOS)]
        params = SimParams(n=n, capacity=g.capacity, **knobs)
        divs = run_scenario_differential(
            scenario, n=n, connect_to=connect_to, seed=seed, steps=steps,
            warm_steps=warm_steps, params=params)
        out.append((f"fuzz:{scenario}:{k}", knobs, divs))
    return out


# ---------------------------------------------------------------------------
# the certificate


def conformance_certificate(scenarios=None, n=48, connect_to=8, seeds=(0,),
                            steps=8, warm_steps=4, waivers_path=None,
                            include_adaptive=True, include_faults=True,
                            include_churn=True, include_gossip=True,
                            include_og=True, fuzz=0, fuzz_seed=0):
    """Run the full conformance fuzz sweep and build the certificate dict:
    every attack scenario x every seed through the per-round differential,
    plus the adaptive-controller, fault-family, churn, and cross-fragment
    entries. fuzz>0 appends that many random-parameter-grid entries
    (run_fuzz_differential). Strict-JSON-safe after sanitize_nonfinite
    (write_certificate)."""
    from ..ops.adversary import SCENARIOS

    if scenarios is None:
        scenarios = SCENARIOS
    waivers = load_waivers(waivers_path)
    entries = []
    for scenario in scenarios:
        divs = []
        for s in seeds:
            divs.extend(run_scenario_differential(
                scenario, n=n, connect_to=connect_to, seed=s, steps=steps,
                warm_steps=warm_steps))
        entries.append(certificate_entry(scenario, divs, waivers,
                                         seeds=list(seeds), n=n, steps=steps))
    if include_adaptive:
        divs = []
        for s in seeds:
            divs.extend(run_adaptive_differential(
                n=n, connect_to=connect_to, seed=s, steps=steps,
                warm_steps=warm_steps))
        entries.append(certificate_entry("adaptive", divs, waivers,
                                         seeds=list(seeds), n=n, steps=steps))
    if include_faults:
        divs = []
        for s in seeds:
            divs.extend(run_faults_differential(
                n=n, connect_to=connect_to, seed=s, steps=steps,
                warm_steps=warm_steps))
        entries.append(certificate_entry("faults", divs, waivers,
                                         seeds=list(seeds), n=n, steps=steps))
    if include_churn:
        divs = []
        for s in seeds:
            divs.extend(run_churn_differential(
                n=n, connect_to=connect_to, seed=s, steps=steps,
                warm_steps=warm_steps))
        entries.append(certificate_entry("churn", divs, waivers,
                                         seeds=list(seeds), n=n, steps=steps))
    if include_og:
        divs = []
        for s in seeds:
            divs.extend(run_og_differential(
                n=n, connect_to=connect_to, seed=s, steps=steps,
                warm_steps=warm_steps))
        entries.append(certificate_entry("opportunistic_graft", divs,
                                         waivers, seeds=list(seeds), n=n,
                                         steps=steps))
    if include_gossip:
        divs = cross_fragment_check(seed=seeds[0])
        entries.append(certificate_entry("gossip_fragments", divs, waivers,
                                         seeds=[seeds[0]], n=64, steps=1))
    if fuzz:
        for name, knobs, divs in run_fuzz_differential(
                fuzz, n=n, connect_to=connect_to, seed=seeds[0],
                steps=steps, warm_steps=warm_steps, fuzz_seed=fuzz_seed):
            entries.append(certificate_entry(
                name, divs, waivers, seeds=[seeds[0]], n=n, steps=steps,
                params=knobs, fuzz_seed=fuzz_seed))
    sim_bugs = sum(e["sim_bugs"] for e in entries)
    return {
        "version": 1,
        "oracle": "ops/spec.py pure-numpy GossipSub v1.1 transition relation "
                  "(ACL2s transcription, arXiv:2311.08859; PRNG-stream "
                  "selection oracle)",
        "float_rtol": FLOAT_RTOL,
        "float_atol": FLOAT_ATOL,
        "entries": entries,
        "sim_bugs": sim_bugs,
        "clean": sim_bugs == 0,
    }


def write_certificate(cert, path):
    """Strict-JSON certificate artifact: sanitize_nonfinite maps any
    non-finite float to null FIRST, then allow_nan=False proves no NaN/inf
    survived anywhere in the tree."""
    from ..runtime.summarize import sanitize_nonfinite

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        json.dump(sanitize_nonfinite(cert), f, indent=2, allow_nan=False)
        f.write("\n")
    return path
