"""graft-audit: static analysis + contracts for the jitted hot paths.

Three engines over one violation model (analysis/report.py):

  - jaxpr auditor (analysis/jaxpr_audit.py): abstractly traces every
    registered entrypoint (analysis/registry.py) and enforces loop/carry/
    cond/donation/compile-key contracts — GA-J*.
  - AST lint (analysis/ast_lint.py): source-level rules over the package's
    jitted scopes and artifact writers — GA-A*.
  - sharding auditor (analysis/sharding_audit.py): compiles every
    registered entrypoint and walks the GSPMD output for collective
    volumes, operand replication, per-device memory and donation aliasing
    — GA-S* — plus the 1M-rung footprint predictor.

CLI: ``python -m dst_libp2p_test_node_tpu lint`` (strict-JSON report,
nonzero exit on findings; ``--sharding`` / ``--predict-rung`` arm engine
3, ``--format github`` adds inline PR annotations). Tier-1 gate:
tests/test_graft_audit.py + tests/test_sharding_audit.py assert the repo
audits clean. The full rule catalog lives in docs/LINT_RULES.md.
"""

from .ast_lint import lint_paths, lint_source
from .contracts import EntrypointContract, LadderRung, TraceSpec
from .jaxpr_audit import audit_contract, audit_contracts, run_checkify
from .report import RULES, Violation, github_annotations, render_report
from .sharding_audit import (audit_sharding_contract,
                             audit_sharding_contracts,
                             contract_sharding_facts,
                             predict_rung_certificate)

__all__ = [
    "EntrypointContract", "LadderRung", "TraceSpec", "Violation", "RULES",
    "audit_contract", "audit_contracts", "run_checkify",
    "audit_sharding_contract", "audit_sharding_contracts",
    "contract_sharding_facts", "predict_rung_certificate",
    "lint_paths", "lint_source", "render_report", "github_annotations",
]
