"""graft-audit: static analysis + contracts for the jitted hot paths.

Two engines over one violation model (analysis/report.py):

  - jaxpr auditor (analysis/jaxpr_audit.py): abstractly traces every
    registered entrypoint (analysis/registry.py) and enforces loop/carry/
    cond/donation/compile-key contracts — GA-J*.
  - AST lint (analysis/ast_lint.py): source-level rules over the package's
    jitted scopes and artifact writers — GA-A*.

CLI: ``python -m dst_libp2p_test_node_tpu lint`` (strict-JSON report,
nonzero exit on findings). Tier-1 gate: tests/test_graft_audit.py asserts
the repo audits clean.
"""

from .ast_lint import lint_paths, lint_source
from .contracts import EntrypointContract, LadderRung, TraceSpec
from .jaxpr_audit import audit_contract, audit_contracts, run_checkify
from .report import RULES, Violation, render_report

__all__ = [
    "EntrypointContract", "LadderRung", "TraceSpec", "Violation", "RULES",
    "audit_contract", "audit_contracts", "run_checkify",
    "lint_paths", "lint_source", "render_report",
]
