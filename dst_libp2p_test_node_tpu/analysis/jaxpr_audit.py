"""Engine 1 — jaxpr auditor: trace the hot entrypoints abstractly and
certify them against their declared contracts.

Everything here is ABSTRACT: ``jax.make_jaxpr`` / ``jax.eval_shape`` /
``jax.jit(...).lower(...)`` trace and lower without touching a device, so
the full audit runs in a few seconds on CPU and is safe in CI.

Codebase-wide rules (applied to every registered entrypoint):

  GA-J001  no pure_callback/io_callback/debug_callback/infeed/outfeed inside
           a scan or while_loop body — a host round-trip per loop iteration
           serializes the fixpoint that the whole design keeps on-device.
  GA-J002  no float64/int64 avals and no weak_type=True avals in loop
           carries. A weak-typed carry (a Python scalar smuggled into the
           carry tuple) re-promotes on every feed-back and is the classic
           silent recompile-churn bug; x64 doubles the state bandwidth.

Contract-driven rules (enabled per entrypoint by its registry entry):

  GA-J003  surviving-``cond`` census >= the declared count (vmapped conds
           lower to ``select_n`` and execute both branches).
  GA-J004  declared donation actually aliases in the lowering text.
  GA-J005  distinct compile keys across the declared ladder match the
           declared count, and feedback outputs' avals match the argument
           avals they are carried back into.
"""

from __future__ import annotations

import inspect
import warnings

from .contracts import EntrypointContract, TraceSpec
from .report import Violation

CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "infeed", "outfeed",
    "host_callback_call",
}
X64_DTYPES = {"float64", "int64", "uint64", "complex128"}

# jaxpr-holding eqn params that mean "this subtree is a loop body"
_LOOP_BODY_PARAMS = {"body_jaxpr"}           # while_loop
_LOOP_COND_PARAMS = {"cond_jaxpr"}           # while_loop predicate
_SCAN_BODY_PARAM = "jaxpr"                   # scan (when primitive is scan)


def _subjaxprs(eqn):
    """Yield (closed_jaxpr, enters_loop_body) for every sub-jaxpr of eqn."""
    import jax

    for key, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else [val]
        for v in vals:
            inner = None
            if isinstance(v, jax.core.ClosedJaxpr):
                inner = v.jaxpr
            elif hasattr(v, "eqns"):
                inner = v
            if inner is None:
                continue
            is_loop = (
                key in _LOOP_BODY_PARAMS or key in _LOOP_COND_PARAMS
                or (eqn.primitive.name == "scan" and key == _SCAN_BODY_PARAM))
            yield inner, is_loop


def iter_eqns(jaxpr, in_loop: bool = False):
    """Depth-first (eqn, in_loop_body) over a jaxpr and all sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        for sub, enters_loop in _subjaxprs(eqn):
            yield from iter_eqns(sub, in_loop or enters_loop)


def primitive_census(jaxpr) -> dict[str, int]:
    counts: dict[str, int] = {}
    for eqn, _ in iter_eqns(jaxpr):
        name = eqn.primitive.name
        counts[name] = counts.get(name, 0) + 1
    return counts


def _src_anchor(fn) -> tuple[str, int]:
    """(file, line) of the entrypoint's def, unwrapping jit wrappers."""
    import os

    target = inspect.unwrap(fn, stop=lambda f: False)
    for attr in ("__wrapped__", "_fun", "func"):
        inner = getattr(target, attr, None)
        if inner is not None and callable(inner):
            target = inner
    try:
        path = inspect.getsourcefile(target) or "<unknown>"
        _, line = inspect.getsourcelines(target)
        return os.path.relpath(path), line
    except (TypeError, OSError):
        return "<unknown>", 0


def trace_entrypoint(spec: TraceSpec):
    """make_jaxpr through a zero-arg closure — statics ride in captured."""
    import jax

    return jax.make_jaxpr(spec.thunk())()


def _carry_avals(eqn):
    """Loop-carried avals of a scan or while eqn."""
    if eqn.primitive.name == "scan":
        inner = eqn.params["jaxpr"].jaxpr
        nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
        return inner.invars[nc:nc + nk]
    if eqn.primitive.name == "while":
        inner = eqn.params["body_jaxpr"].jaxpr
        nb = eqn.params["body_nconsts"]
        return inner.invars[nb:]
    return []


def _check_loop_rules(closed, name, file, line) -> list[Violation]:
    out = []
    for eqn, in_loop in iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim in CALLBACK_PRIMS and in_loop:
            out.append(Violation(
                rule="GA-J001", file=file, line=line, entrypoint=name,
                message=f"{prim} inside a scan/while body — one host "
                        "round-trip per loop iteration"))
        if prim in ("scan", "while"):
            for var in _carry_avals(eqn):
                aval = var.aval
                dt = str(getattr(aval, "dtype", ""))
                weak = bool(getattr(aval, "weak_type", False))
                if dt in X64_DTYPES:
                    out.append(Violation(
                        rule="GA-J002", file=file, line=line, entrypoint=name,
                        message=f"{prim} carry aval {aval} is x64 — double "
                                "state bandwidth in the hot loop"))
                elif weak:
                    out.append(Violation(
                        rule="GA-J002", file=file, line=line, entrypoint=name,
                        message=f"{prim} carry aval {aval} is weak-typed — "
                                "a Python scalar in the carry re-promotes "
                                "every feed-back (recompile churn); wrap it "
                                "in jnp.asarray with an explicit dtype"))
    return out


def _check_cond_survival(closed, contract, file, line) -> list[Violation]:
    census = primitive_census(closed.jaxpr)
    got = census.get("cond", 0)
    want = contract.expected_conds
    if got >= want:
        return []
    return [Violation(
        rule="GA-J003", file=file, line=line, entrypoint=contract.name,
        message=f"expected >= {want} surviving lax.cond branch(es), found "
                f"{got} (select_n count: {census.get('select_n', 0)}) — a "
                "batched predicate lowered the branch to select_n, so BOTH "
                "sides now execute every call")]


def _check_donation(spec, contract, file, line) -> list[Violation]:
    import jax

    def positional(*dyn):
        return spec.fn(*dyn, **spec.kwargs)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = jax.jit(
            positional, donate_argnums=contract.donate).lower(*spec.args)
        text = lowered.as_text()
    unusable = [w for w in caught
                if "donated buffers were not usable" in str(w.message)]
    if "tf.aliasing_output" in text and not unusable:
        return []
    detail = str(unusable[0].message) if unusable else \
        "no tf.aliasing_output annotation in the lowering"
    return [Violation(
        rule="GA-J004", file=file, line=line, entrypoint=contract.name,
        message=f"declared donation of args {contract.donate} does not hold "
                f"in the lowering ({detail}) — the donated buffers would be "
                "copied, not reused")]


def _leaf_fingerprint(tree):
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for leaf in leaves:
        aval = jax.api_util.shaped_abstractify(leaf)
        out.append((tuple(aval.shape), str(aval.dtype),
                    bool(getattr(aval, "weak_type", False))))
    return tuple(out)


def _check_compile_keys(contract, file, line) -> list[Violation]:
    rungs = contract.ladder()
    keys = {}
    for rung in rungs:
        key = (repr(rung.statics), _leaf_fingerprint(rung.dynamic))
        keys.setdefault(key, []).append(rung.name)
    want = contract.expected_compile_keys
    if want is None:
        want = len(rungs)
    if len(keys) == want:
        return []
    detail = "; ".join(",".join(v) for v in keys.values())
    return [Violation(
        rule="GA-J005", file=file, line=line, entrypoint=contract.name,
        message=f"expected {want} distinct compile key(s) across the ladder, "
                f"got {len(keys)} (groups: {detail}) — an aval or weak-type "
                "drift is splitting (or collapsing) the jit cache")]


def _check_feedback(spec, contract, file, line) -> list[Violation]:
    import jax

    out_shapes = jax.eval_shape(spec.thunk())
    violations = []
    for out_get, arg_get in contract.feedback:
        fed = out_get(out_shapes)
        arg = arg_get(spec)
        fed_fp = _leaf_fingerprint(fed)
        arg_fp = _leaf_fingerprint(arg)
        if fed_fp == arg_fp:
            continue
        diffs = [i for i, (a, b) in enumerate(zip(fed_fp, arg_fp)) if a != b]
        if len(fed_fp) != len(arg_fp):
            what = f"leaf count {len(fed_fp)} vs {len(arg_fp)}"
        else:
            i = diffs[0]
            what = f"leaf {i}: out {fed_fp[i]} vs arg {arg_fp[i]}"
        violations.append(Violation(
            rule="GA-J005", file=file, line=line, entrypoint=contract.name,
            message=f"feedback aval drift ({what}) — feeding this output "
                    "back recompiles the entrypoint every iteration"))
    return violations


def audit_contract(contract: EntrypointContract) -> list[Violation]:
    """All static checks for one registered entrypoint."""
    spec = contract.build()
    file, line = _src_anchor(spec.fn)
    violations: list[Violation] = []
    try:
        closed = trace_entrypoint(spec)
    except Exception as e:  # a trace failure is itself a finding
        return [Violation(
            rule="GA-J001", file=file, line=line, entrypoint=contract.name,
            message=f"entrypoint failed to trace abstractly: {e!r}")]
    violations += _check_loop_rules(closed, contract.name, file, line)
    if contract.expected_conds is not None:
        violations += _check_cond_survival(closed, contract, file, line)
    if contract.donate is not None:
        violations += _check_donation(spec, contract, file, line)
    if contract.ladder is not None:
        violations += _check_compile_keys(contract, file, line)
    if contract.feedback:
        violations += _check_feedback(spec, contract, file, line)
    return violations


def audit_contracts(contracts) -> list[Violation]:
    out: list[Violation] = []
    for c in contracts:
        out.extend(audit_contract(c))
    return out


def run_checkify(contracts) -> list[Violation]:
    """Opt-in runtime half: execute each contract's checkify thunk on the
    canonical small config (CONCRETE execution — not part of the static
    gate). A failed check surfaces as a violation with the check message."""
    out: list[Violation] = []
    for c in contracts:
        if c.runtime_check is None:
            continue
        spec = c.build()
        file, line = _src_anchor(spec.fn)
        try:
            c.runtime_check()
        except Exception as e:
            out.append(Violation(
                rule="GA-J005", file=file, line=line, entrypoint=c.name,
                message=f"runtime contract failed: {e}"))
    return out
