"""Contract model for graft-audit's jaxpr engine.

An EntrypointContract declares, once per hot entrypoint, everything the
static auditor needs to certify it without running it:

  - ``build``: a zero-arg thunk returning a TraceSpec (fn + concrete small
    args). The auditor traces ``lambda: fn(*args, **kwargs)`` abstractly —
    closure capture sidesteps all static-argument plumbing, and nothing
    executes on a device.
  - ``expected_conds``: the number of scalar-predicate ``lax.cond`` branches
    that must SURVIVE in the traced jaxpr. The simulator's perf story leans
    on real XLA branches (steady-state heartbeat skips, the serialized-answer
    repair, the warm-start cold rerun); a refactor that lets vmap batch one
    of those predicates silently lowers it to ``select_n`` and executes both
    sides every call. A surviving-cond count below the declared number is
    exactly that regression (rule GA-J003).
  - ``donate``: positional arg indices whose buffers the caller may donate.
    The auditor lowers ``jax.jit(fn, donate_argnums=donate)`` and requires
    the ``tf.aliasing_output`` annotations to actually appear — donation
    that silently fails to alias is a 2x memory bill at the 1M-peer ladder
    rung (rule GA-J004).
  - ``ladder``: named aval families (miniatures of the bench ladder rungs).
    Distinct compile keys — (static args, leaf avals incl. weak_type) —
    must number exactly ``expected_compile_keys`` (rule GA-J005).
  - ``feedback``: (out_get, arg_get) pairs for carried outputs (e.g. the
    new SimState fed back into the next publish). Output avals must equal
    the argument avals leaf-for-leaf, or every iteration recompiles
    (rule GA-J005).
  - ``runtime_check``: opt-in checkify half — a thunk that runs the
    entrypoint CONCRETELY on the canonical config under
    ``jax.experimental.checkify`` and asserts value-level invariants the
    static engine cannot see (mesh-degree bounds, non-negative delays).

Registering a new entrypoint = adding one EntrypointContract to
``registry.default_contracts()``; the audit CLI and the tier-1 gate pick it
up automatically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class TraceSpec:
    fn: Callable
    args: tuple = ()
    kwargs: dict = dataclasses.field(default_factory=dict)

    def thunk(self) -> Callable[[], Any]:
        return lambda: self.fn(*self.args, **self.kwargs)


@dataclasses.dataclass
class LadderRung:
    """One aval family for compile-key counting: a name, the hashable
    static-argument fingerprint, and the dynamic arg pytree."""
    name: str
    statics: Any              # hashable fingerprint (e.g. the SimParams)
    dynamic: Any              # pytree of arrays / scalars


@dataclasses.dataclass
class EntrypointContract:
    name: str
    build: Callable[[], TraceSpec]
    expected_conds: int | None = None
    donate: tuple[int, ...] | None = None
    ladder: Callable[[], list[LadderRung]] | None = None
    expected_compile_keys: int | None = None
    # each pair: (output_getter(outputs) -> pytree, arg_getter(spec) -> pytree)
    feedback: list[tuple[Callable, Callable]] = dataclasses.field(
        default_factory=list)
    runtime_check: Callable[[], None] | None = None
    # retrace budget (runtime/profiling.py): the number of "Finished tracing
    # + compiling" events a SECOND call of the representative spec with
    # same-aval inputs may trigger. 0 — the default, and the value for every
    # shipped contract — means the second call must be a pure jit-cache hit;
    # any miss is weak-type/shape drift at the call boundary (the PR 1/PR 3
    # carry bugs) and fails tier-1 (tests/test_profiling.py).
    retrace_budget: int = 0
    # --- sharding auditor (analysis/sharding_audit.py, GA-S family) ---
    # collectives: the declared collective-op budget SET — every collective
    # kind GSPMD may insert into this contract's compiled program
    # (all-gather / all-reduce / reduce-scatter / collective-permute /
    # all-to-all). Like retrace_budget, it is a ratchet: a kind that shows
    # up in the compiled HLO without being declared here is GA-S002 (an
    # unbudgeted cross-device data movement snuck into the hot window).
    # None (the default) opts the contract out — right for single-device
    # entrypoints; every contract traced on a multi-device mesh should
    # declare one, even if empty (frozenset() = "no collectives allowed").
    collectives: frozenset | None = None
    # per-compile ceiling on the summed per-device byte volume of all
    # collective outputs at the contract's canonical audit shape (GA-S003);
    # None = unbudgeted
    collective_bytes_budget: int | None = None
    # per-device peak-memory ceiling (argument + output + temp − aliased,
    # XLA memory_analysis) at the canonical audit shape (GA-S004);
    # None = unbudgeted
    hbm_budget_bytes: int | None = None
    # --- DCN-axis scoping (GA-S006) ---
    # dcn_block_devices: devices per DCN block (= per process) on the
    # contract's canonical 3-level audit mesh. When set, the auditor parses
    # every collective's replica_groups and splits its per-device bytes by
    # scope: a group whose partition ids span >= 2 blocks moves data across
    # the DCN boundary. None (the default) leaves the rule off — right for
    # every contract traced on a 1- or 2-level mesh.
    dcn_block_devices: int | None = None
    # ceiling on the summed per-device bytes of CROSS-DCN collective
    # outputs (GA-S006). The design target for the dcn x trials x peers
    # grid is literally zero: trials are embarrassingly parallel across
    # processes and every peer-axis collective must stay inside one ICI
    # block, so any cross-DCN byte means the partitioner stopped seeing
    # the placement the grid was designed around.
    dcn_collective_bytes_budget: int = 0
    # pinned waivers: ((rule_id, rationale), ...). A finding whose rule is
    # waived here is recorded in the report's "waived" block with its
    # rationale instead of failing the gate — the docs/LINT_RULES.md waiver
    # table mirrors these. A waiver names a deliberate modeling choice, not
    # an escape hatch (same discipline as docs/CONFORMANCE.md).
    waivers: tuple = ()
    notes: str = ""
