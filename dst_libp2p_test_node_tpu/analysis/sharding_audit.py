"""Engine 3 — sharding auditor: static GSPMD collective/footprint analysis.

The jaxpr engine (GA-J*) certifies the traced program and the AST engine
(GA-A*) the source, but neither sees what GSPMD actually EMITS for the
nested trials x peers grid: a contract can pass every "sharded == vmapped"
equality test while silently replicating a large operand across the peer
axis or inserting an unbudgeted all-gather per scan iteration. This engine
closes that gap statically — ``jax.jit(...).lower(...).compile()`` plus a
walk of the compiled HLO text; nothing executes on a device:

  collectives          every all-gather / all-reduce / reduce-scatter /
                       collective-permute / all-to-all in the compiled
                       module, with per-device output byte volumes parsed
                       from the HLO result shapes (async -start halves are
                       skipped so a split op counts once)
  operand shardings    ``compiled.input_shardings`` leaves paired 1:1 with
                       the dynamic-argument pytree ``lower_spec`` lowered
                       against, so every replicated operand is named by its
                       pytree path, not an HLO parameter index
  per-device memory    XLA's ``memory_analysis`` (argument + output + temp
                       − aliased), the same surface entrypoint_cost reads
  donation             ``input_output_alias`` in the COMPILED output — the
                       stage after GA-J004's lowering-text check, where XLA
                       can still drop an alias it accepted at lowering time

Rules (GA-S family; declarations live on EntrypointContract):

  GA-S001  operand >= the large floor fully replicated inside a
           multi-partition program
  GA-S002  collective kind in the compiled HLO absent from the contract's
           declared ``collectives`` budget set
  GA-S003  summed per-device collective bytes over ``collective_bytes_budget``
  GA-S004  per-device peak memory over ``hbm_budget_bytes``
  GA-S005  declared donation not aliased in the compiled output
  GA-S006  collective bytes crossing the DCN axis over
           ``dcn_collective_bytes_budget`` — replica groups parsed from the
           compiled HLO (explicit and iota forms) and classified against
           the process-major ``dcn_block_devices`` blocking, so "zero
           peer-axis bytes ever cross a host boundary" is a statically
           gated property of the 3-level dcn x trials x peers grid

A finding whose rule is pinned in ``contract.waivers`` lands in the
report's "waived" block with its rationale instead of failing the gate
(docs/LINT_RULES.md holds the mirror table).

On top of the extractor sits the memory scaling predictor
(``predict_rung_certificate``): lower the attack-window program at 3–4
peer counts, fit per-leaf footprint power laws, hold out the largest point
to validate the fit, and extrapolate to the 1M rung
(bench_configs config 8, ``ATTACK_RUNG_PEERS=1048576``) on a modeled
v5e-8 — a compile-time fits / does-not-fit verdict with per-leaf
attribution, before any TPU time is spent.
"""

from __future__ import annotations

import math
import re

from .contracts import EntrypointContract
from .jaxpr_audit import _src_anchor
from .report import Violation

# the collective kinds GSPMD inserts for sharded programs; -start/-done
# suffixed forms are the async-split halves of the same logical op
COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")

# default GA-S001 floor: operands below this are latency constants and
# per-trial scalars whose replication is the intended layout; at the
# canonical audit shapes anything >= 2 KiB is a real per-peer table
REPLICATED_FLOOR_BYTES = 2048

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# one compiled-HLO instruction: `%name = SHAPE kind(...)`; SHAPE may be a
# single `dtype[dims]{layout}` or a tuple of them (async forms, multi-
# operand all-reduces)
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<shape>\(?[a-z0-9_]+\[[^=]*?)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<suffix>-start|-done)?\(")

_SHAPE_RE = re.compile(r"(pred|bf16|[sufc]\d+)\[([0-9,]*)\]")

_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def _shape_bytes(shape_text: str) -> int:
    """Byte volume of an HLO shape token (sums tuple components)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * _DTYPE_BYTES.get(dtype, 4)
    return total


def collect_collectives(hlo_text: str) -> dict[str, dict]:
    """{kind: {count, per_device_bytes}} over a compiled HLO module.

    Byte volumes are the per-device RESULT shapes — what each chip
    materializes per execution of the op. The async ``-start`` half is
    skipped (its tuple carries the in-flight buffers the ``-done`` result
    already accounts for), so a split collective counts once."""
    found: dict[str, dict] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("suffix") == "-start":
            continue
        kind = m.group("kind")
        entry = found.setdefault(kind, {"count": 0, "per_device_bytes": 0})
        entry["count"] += 1
        entry["per_device_bytes"] += _shape_bytes(m.group("shape"))
    return found


def _num_partitions(hlo_text: str) -> int:
    m = _PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else 1


# replica_groups in compiled HLO: explicit `{{0,1},{2,3}}`, empty `{}`
# (one group over everything), or the iota form `[G,S]<=[dims]` with an
# optional transpose `T(perm)` (XLA's compact encoding for regular grids).
# collective-permute carries source_target_pairs instead — each {src,dst}
# pair is its own two-member "group" for scope classification.
_REPLICA_GROUPS_RE = re.compile(
    r"(?:replica_groups|source_target_pairs)="
    r"(\{\{[0-9,{}\s]*\}\}|\{\}|"
    r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")


def _parse_replica_groups(instr_text: str) -> list[list[int]] | None:
    """Partition-id groups of one collective instruction, or None when the
    instruction carries no replica_groups attribute. The empty `{}` form
    returns [] — caller-side that means "one group spanning everything"."""
    m = _REPLICA_GROUPS_RE.search(instr_text)
    if not m:
        return None
    tok = m.group(1)
    if tok == "{}":
        return []
    if tok.startswith("{"):
        groups = []
        for grp in re.findall(r"\{([0-9,\s]*)\}", tok):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(ids)
        return groups
    import numpy as np

    im = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", tok)
    out_dims = [int(x) for x in im.group(1).split(",")]
    reshape_dims = [int(x) for x in im.group(2).split(",")]
    ids = np.arange(math.prod(reshape_dims)).reshape(reshape_dims)
    if im.group(3):
        ids = ids.transpose([int(x) for x in im.group(3).split(",")])
    ids = ids.reshape(out_dims)
    return [[int(i) for i in row] for row in ids]


def collect_collective_scopes(hlo_text: str, block_devices: int,
                              num_partitions: int | None = None) -> dict:
    """Split per-device collective bytes by DCN scope (the GA-S006 fact).

    `block_devices` is the per-process device count on the 3-level
    dcn x trials x peers mesh; make_dcn_mesh orders devices process-major,
    so partition id // block_devices IS the DCN block index. A collective
    whose replica group spans >= 2 blocks moves bytes across the DCN
    boundary; everything else stays on one process's ICI submesh. A
    collective with no / empty replica_groups is conservatively cross-DCN
    whenever the program has more partitions than one block holds."""
    if num_partitions is None:
        num_partitions = _num_partitions(hlo_text)
    bytes_by = {"intra_process": 0, "cross_dcn": 0}
    cross_kinds: dict[str, int] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("suffix") == "-start":
            continue
        nl = hlo_text.find("\n", m.end())
        instr = hlo_text[m.start():nl if nl >= 0 else len(hlo_text)]
        groups = _parse_replica_groups(instr)
        if not groups:  # absent or the empty all-spanning form
            spans = num_partitions > block_devices
        else:
            spans = any(
                len({i // block_devices for i in g}) > 1 for g in groups)
        vol = _shape_bytes(m.group("shape"))
        if spans:
            bytes_by["cross_dcn"] += vol
            kind = m.group("kind")
            cross_kinds[kind] = cross_kinds.get(kind, 0) + 1
        else:
            bytes_by["intra_process"] += vol
    return {"bytes": bytes_by, "cross_dcn_kinds": cross_kinds}


def _is_sharding(x) -> bool:
    return hasattr(x, "is_fully_replicated")


def operand_facts(compiled, dyn) -> list[dict]:
    """Per input leaf: pytree path name, global/per-device bytes, per-dim
    partition counts, replication flag. ``dyn`` is the (dyn_args,
    dyn_kwargs) pytree ``lower_spec(..., return_dynamic=True)`` returned —
    its flattened leaves align 1:1 with ``compiled.input_shardings`` leaves
    (both flatten the lowered call's positional signature)."""
    import jax
    import numpy as np

    shardings = jax.tree_util.tree_leaves(
        compiled.input_shardings[0], is_leaf=_is_sharding)
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(dyn)
    if len(shardings) != len(leaves_with_path):  # pragma: no cover
        raise RuntimeError(
            f"input_shardings leaves ({len(shardings)}) do not align with "
            f"the dynamic-argument pytree ({len(leaves_with_path)})")
    out = []
    for (path, leaf), sh in zip(leaves_with_path, shardings):
        shape = tuple(int(d) for d in np.shape(leaf))
        itemsize = int(np.asarray(leaf).dtype.itemsize) if shape or True \
            else 1
        global_bytes = int(math.prod(shape)) * itemsize if shape else itemsize
        try:
            shard = tuple(int(d) for d in sh.shard_shape(shape))
        except Exception:  # pragma: no cover - exotic sharding types
            shard = shape
        per_dim = tuple(
            (g // s if s else 1) for g, s in zip(shape, shard)) or (1,)
        per_device = int(math.prod(shard)) * itemsize if shard else itemsize
        out.append({
            "name": jax.tree_util.keystr(path),
            "shape": list(shape),
            "global_bytes": global_bytes,
            "per_device_bytes": per_device,
            "partitions_per_dim": list(per_dim),
            "replicated": bool(sh.is_fully_replicated),
        })
    return out


def memory_facts(compiled) -> dict | None:
    """Per-device {arguments, outputs, temp, aliased, peak} bytes from
    XLA's memory analysis; None when the backend does not expose it."""
    try:
        ma = compiled.memory_analysis()
        args = int(ma.argument_size_in_bytes)
        outs = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
    except Exception:
        return None
    return {"arguments": args, "outputs": outs, "temp": temp,
            "aliased": alias, "peak": args + outs + temp - alias}


def _compile_spec(spec):
    from ..runtime.profiling import lower_spec

    # keep_unused: pruned parameters would misalign input_shardings with
    # the dynamic-argument pytree (and hide a replicated-but-unread
    # operand from GA-S001, which is still worth flagging — production
    # callers pay its transfer either way)
    lowered, dyn = lower_spec(spec, return_dynamic=True, keep_unused=True)
    return lowered.compile(), dyn


def _donation_aliased(spec, donate: tuple[int, ...]) -> bool:
    """True iff the donated compile carries an input_output_alias — the
    compiled-output stage of GA-J004's lowering-text check."""
    import warnings

    import jax

    def positional(*dyn):
        return spec.fn(*dyn, **spec.kwargs)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        compiled = jax.jit(
            positional, donate_argnums=donate).lower(*spec.args).compile()
    return "input_output_alias" in compiled.as_text()


def contract_sharding_facts(
        contract: EntrypointContract, *,
        repl_floor_bytes: int = REPLICATED_FLOOR_BYTES) -> dict:
    """Compile the contract's representative spec and extract the GSPMD
    facts block (strict-JSON safe). Pure analysis — rule enforcement is
    ``audit_sharding_contract``."""
    spec = contract.build()
    compiled, dyn = _compile_spec(spec)
    hlo = compiled.as_text()
    operands = operand_facts(compiled, dyn)
    collectives = collect_collectives(hlo)
    mem = memory_facts(compiled)
    partitions = _num_partitions(hlo)
    facts = {
        "num_partitions": partitions,
        "collectives": collectives,
        "collective_bytes": sum(
            c["per_device_bytes"] for c in collectives.values()),
        "memory": mem,
        "replicated_operands": [
            {"name": o["name"], "bytes": o["global_bytes"]}
            for o in operands
            if o["replicated"] and o["global_bytes"] >= repl_floor_bytes],
        "operands": len(operands),
        "argument_bytes_per_device": sum(
            o["per_device_bytes"] for o in operands),
    }
    if contract.dcn_block_devices:
        scope = collect_collective_scopes(
            hlo, contract.dcn_block_devices, num_partitions=partitions)
        facts["collective_bytes_by_scope"] = scope["bytes"]
        facts["cross_dcn_collectives"] = scope["cross_dcn_kinds"]
    if contract.donate:
        facts["donation_aliased"] = _donation_aliased(spec, contract.donate)
    return facts


def audit_sharding_contract(
        contract: EntrypointContract, *,
        repl_floor_bytes: int = REPLICATED_FLOOR_BYTES,
) -> tuple[list[Violation], list[dict], dict]:
    """(violations, waived, facts) for one contract under the GA-S rules.

    Waivers pinned on the contract move their findings into the waived
    list (each with the pinned rationale) instead of the violation list."""
    spec = contract.build()
    file, line = _src_anchor(spec.fn)
    facts = contract_sharding_facts(
        contract, repl_floor_bytes=repl_floor_bytes)
    found: list[Violation] = []

    if facts["num_partitions"] > 1:
        for rep in facts["replicated_operands"]:
            found.append(Violation(
                rule="GA-S001", file=file, line=line,
                entrypoint=contract.name,
                message=f"operand {rep['name']} ({rep['bytes']} B) is fully "
                        f"replicated across all {facts['num_partitions']} "
                        "partitions of a sharded contract — every device "
                        "pays its full footprint"))

    if contract.collectives is not None:
        declared = {str(k) for k in contract.collectives}
        for kind in sorted(facts["collectives"]):
            if kind not in declared:
                c = facts["collectives"][kind]
                found.append(Violation(
                    rule="GA-S002", file=file, line=line,
                    entrypoint=contract.name,
                    message=f"compiled HLO contains {c['count']} {kind} "
                            f"op(s) ({c['per_device_bytes']} B/device) not "
                            "in the contract's declared collectives budget "
                            f"set {sorted(declared)}"))

    if contract.collective_bytes_budget is not None:
        total = facts["collective_bytes"]
        if total > contract.collective_bytes_budget:
            found.append(Violation(
                rule="GA-S003", file=file, line=line,
                entrypoint=contract.name,
                message=f"collective output volume {total} B/device exceeds "
                        f"the declared budget "
                        f"{contract.collective_bytes_budget} B/device at "
                        "the canonical audit shape"))

    if contract.hbm_budget_bytes is not None and facts["memory"]:
        peak = facts["memory"]["peak"]
        if peak > contract.hbm_budget_bytes:
            found.append(Violation(
                rule="GA-S004", file=file, line=line,
                entrypoint=contract.name,
                message=f"per-device peak memory {peak} B exceeds the "
                        f"declared HBM budget {contract.hbm_budget_bytes} B "
                        "at the canonical audit shape"))

    if contract.dcn_block_devices:
        cross = facts["collective_bytes_by_scope"]["cross_dcn"]
        if cross > contract.dcn_collective_bytes_budget:
            kinds = facts["cross_dcn_collectives"]
            found.append(Violation(
                rule="GA-S006", file=file, line=line,
                entrypoint=contract.name,
                message=f"collectives {sorted(kinds)} move {cross} B/device "
                        "across the DCN axis (replica groups spanning >= 2 "
                        f"{contract.dcn_block_devices}-device process "
                        "blocks) — budget "
                        f"{contract.dcn_collective_bytes_budget} B; "
                        "peer-axis traffic must stay inside one ICI block"))

    if contract.donate and facts.get("donation_aliased") is False:
        found.append(Violation(
            rule="GA-S005", file=file, line=line, entrypoint=contract.name,
            message=f"declared donation of args {contract.donate} carries "
                    "no input_output_alias in the COMPILED output — the "
                    "lowering may annotate it, but XLA dropped the alias, "
                    "so the donated buffers are copied"))

    waiver_rationale = {rule: why for rule, why in contract.waivers}
    violations, waived = [], []
    for v in found:
        if v.rule in waiver_rationale:
            w = v.to_dict()
            w["rationale"] = waiver_rationale[v.rule]
            waived.append(w)
        else:
            violations.append(v)
    return violations, waived, facts


def audit_sharding_contracts(
        contracts, *, repl_floor_bytes: int = REPLICATED_FLOOR_BYTES,
) -> tuple[list[Violation], list[dict], dict]:
    """Audit many contracts: (violations, waived, facts_by_name). A
    contract that cannot compile on this backend reports an ``error``
    fact instead of aborting the sweep (the report must keep emitting)."""
    violations: list[Violation] = []
    waived: list[dict] = []
    facts: dict = {}
    for c in contracts:
        try:
            v, w, f = audit_sharding_contract(
                c, repl_floor_bytes=repl_floor_bytes)
        except Exception as e:  # noqa: BLE001 — per-entry degradation
            facts[c.name] = {"error": repr(e)[:200]}
            continue
        violations.extend(v)
        waived.extend(w)
        facts[c.name] = f
    return violations, waived, facts


# ------------------------------------------------- rung predictor

# the modeled target: one v5e-8 slice, 16 GiB HBM per chip, the 2x4
# trials x peers grid bench_configs config 8 runs (2 trial groups, each
# group's peer submesh 4 chips wide)
RUNG_PEERS = 1_048_576
V5E8_CHIPS = 8
V5E8_HBM_BYTES = 16 * 2**30
RUNG_TRIAL_GROUPS = 2
RUNG_PEER_WIDTH = 4


def fit_power_law(ns, ys) -> tuple[float, float]:
    """(coeff, exponent) of y = coeff * n**exponent by least squares in
    log2-log2 space. Constant series fit exactly as exponent 0; an
    all-zero series returns (0, 0)."""
    pts = [(n, y) for n, y in zip(ns, ys) if y > 0]
    if not pts:
        return 0.0, 0.0
    if len(pts) == 1 or len({y for _, y in pts}) == 1:
        return float(pts[0][1]), 0.0
    lx = [math.log2(n) for n, _ in pts]
    ly = [math.log2(y) for _, y in pts]
    k = len(pts)
    mx, my = sum(lx) / k, sum(ly) / k
    sxx = sum((x - mx) ** 2 for x in lx)
    sxy = sum((x - mx) * (y - my) for x, y in zip(lx, ly))
    p = sxy / sxx if sxx else 0.0
    a = 2.0 ** (my - p * mx)
    return float(a), float(p)


def _eval_fit(fit: tuple[float, float], n: int) -> float:
    a, p = fit
    return a * float(n) ** p


def _rung_partitions(leaf: dict, trials: int, mesh_shape: dict,
                     dcn: int = 1) -> tuple[int, bool]:
    """(partition count on the MODELED rung grid, trial-axis flag) of one
    input leaf, inferred from its measured per-dim partition counts on the
    audit grid.

    Layout rule (parallel/sharding.nested_batch_shardings): stacked
    peer-major (T, N, ...) leaves split over both axes; (T, ...) per-trial
    leaves over trials only; shared (N, ...) graph arrays over the peer
    submesh. The measured per-dim counts identify which grid axes a leaf
    actually occupies — dim 0 of size T is the trial axis, any other
    partitioned dim is the peer axis — and the rung factor re-evaluates
    those axes at the rung grid's extents. On a modeled multi-host pod
    (`dcn` > 1) the trial axis additionally splits over the DCN blocks —
    the stacked-trial extent grows dcn-fold and so does its partition
    count, so a trial leaf's per-device bytes are DCN-invariant while the
    pod's GLOBAL trial throughput scales with the process count."""
    g_cur = int(mesh_shape.get("trials", 1))
    per_dim = leaf["partitions_per_dim"]
    shape = leaf["shape"]
    factor, on_trials = 1, False
    for d, (size, parts) in enumerate(zip(shape, per_dim)):
        if parts <= 1:
            continue
        on_trial_axis = (d == 0 and size == trials and parts <= g_cur)
        if on_trial_axis:
            on_trials = True
            factor *= RUNG_TRIAL_GROUPS * dcn
        else:
            factor *= RUNG_PEER_WIDTH
    return factor, on_trials


def predict_rung_certificate(
        peer_counts=(64, 128, 256, 512), *, rung_peers: int = RUNG_PEERS,
        steps: int = 20, connect_to: int = 10, local_trials: int = 2,
        hbm_bytes: int = V5E8_HBM_BYTES, spec_builder=None,
        dcn: int = 1, scenario: str = "sybil_graft_flood") -> dict:
    """Lower the config-8 attack-window program at several peer counts,
    fit per-leaf footprint power laws, and emit the strict-JSON rung
    feasibility certificate for a modeled v5e-8 (or, with ``dcn`` > 1, a
    modeled ``dcn``-host pod of v5e-8 slices joined over DCN).

    Per fit point: every input leaf's GLOBAL bytes (grid-independent) plus
    the per-device output/temp totals from XLA's memory analysis. Input
    leaves extrapolate as global_fit(rung_peers) / rung_partitions(leaf);
    output/temp extrapolate per-device and re-scale by the audit-grid /
    rung-grid peer-width ratio (they are row-block-proportional). The
    largest point is held out to validate the fit (acceptance bar: within
    10%); the final extrapolation refits on every point.

    The DCN factor models the make_dcn_mesh placement: each host runs the
    2 x 4 trials x peers grid on its own stacked-trial slice, so a
    trial-axis leaf's global bytes AND partitions both scale by ``dcn``
    (per-device unchanged), shared peer-axis arrays replicate per block,
    and the fits-or-not verdict stays a per-chip HBM question — what
    changes at 4M peers is the leaves' n-scaling, not the grid math."""
    from ..parallel.sharding import make_trial_mesh
    from .registry import attack_rung_spec

    if spec_builder is None:
        def spec_builder(n):
            return attack_rung_spec(
                n, steps=steps, connect_to=connect_to,
                local_trials=local_trials)

    peer_counts = sorted(int(n) for n in peer_counts)
    if len(peer_counts) < 3:
        raise ValueError("need >= 3 peer counts to fit and validate")
    if dcn < 1:
        raise ValueError(f"dcn must be >= 1, got {dcn}")
    mesh = make_trial_mesh(RUNG_TRIAL_GROUPS)
    mesh_shape = {k: int(v) for k, v in mesh.shape.items()}
    trials = RUNG_TRIAL_GROUPS * local_trials
    width_scale = mesh_shape.get("peers", 1) / RUNG_PEER_WIDTH

    points = []
    for n in peer_counts:
        compiled, dyn = _compile_spec(spec_builder(n))
        ops = operand_facts(compiled, dyn)
        mem = memory_facts(compiled)
        if mem is None:
            raise RuntimeError(
                "backend exposes no memory_analysis — cannot fit the rung "
                "footprint")
        points.append({"peers": n, "operands": ops, "memory": mem})

    names = [o["name"] for o in points[0]["operands"]]
    if any([o["name"] for o in pt["operands"]] != names for pt in points):
        raise RuntimeError("operand pytree drifted across fit points")

    def leaf_series(pts):
        ns = [pt["peers"] for pt in pts]
        series = {}
        for i, name in enumerate(names):
            series[name] = (ns, [pt["operands"][i]["global_bytes"]
                                 for pt in pts])
        return series

    def predict_per_device(pts, n):
        """Fitted per-device total at peer count n ON THE AUDIT GRID —
        comparable with a direct lowering's memory_analysis at n."""
        total = 0.0
        for i, name in enumerate(names):
            ns = [pt["peers"] for pt in pts]
            fit = fit_power_law(ns, [pt["operands"][i]["global_bytes"]
                                     for pt in pts])
            parts = max(pt["operands"][i]["global_bytes"]
                        // max(pt["operands"][i]["per_device_bytes"], 1)
                        for pt in pts) or 1
            total += _eval_fit(fit, n) / parts
        for key in ("outputs", "temp"):
            ns = [pt["peers"] for pt in pts]
            fit = fit_power_law(ns, [pt["memory"][key] for pt in pts])
            total += _eval_fit(fit, n)
        return total

    # held-out validation at the largest point
    held = points[-1]
    predicted = predict_per_device(points[:-1], held["peers"])
    measured = (held["memory"]["arguments"] + held["memory"]["outputs"]
                + held["memory"]["temp"] - held["memory"]["aliased"])
    # the argument fit predicts pre-aliasing totals; compare against the
    # same surface
    measured_raw = (held["memory"]["arguments"] + held["memory"]["outputs"]
                    + held["memory"]["temp"])
    rel_err = abs(predicted - measured_raw) / max(measured_raw, 1)

    # final extrapolation refits on every point
    ns_all = [pt["peers"] for pt in points]
    leaves_out = []
    arg_total = 0.0
    for i, name in enumerate(names):
        ys = [pt["operands"][i]["global_bytes"] for pt in points]
        fit = fit_power_law(ns_all, ys)
        parts, on_trials = _rung_partitions(
            points[-1]["operands"][i], trials, mesh_shape, dcn=dcn)
        pred_global = _eval_fit(fit, rung_peers)
        if on_trials:
            # dcn x more stacked trials on the modeled pod; the matching
            # dcn factor inside `parts` keeps per-device bytes invariant
            pred_global *= dcn
        pred_dev = pred_global / parts
        arg_total += pred_dev
        leaves_out.append({
            "name": name,
            "bytes_at_largest_fit_point": ys[-1],
            "coeff": round(fit[0], 6),
            "exponent": round(fit[1], 6),
            "rung_partitions": parts,
            "predicted_global_bytes": int(pred_global),
            "predicted_per_device_bytes": int(pred_dev),
        })
    leaves_out.sort(key=lambda x: (-x["predicted_per_device_bytes"],
                                   x["name"]))
    mem_out = {}
    for key in ("outputs", "temp"):
        fit = fit_power_law(ns_all, [pt["memory"][key] for pt in points])
        mem_out[key] = int(_eval_fit(fit, rung_peers) / width_scale
                           if width_scale else 0)
    total = int(arg_total) + mem_out["outputs"] + mem_out["temp"]
    utilization = total / hbm_bytes

    return {
        "rung": {
            "peers": int(rung_peers), "trials": trials * dcn,
            "trial_groups": RUNG_TRIAL_GROUPS,
            "peer_width": RUNG_PEER_WIDTH,
            "dcn": int(dcn),
            "attack_heartbeats": int(steps),
            "connect_to": int(connect_to),
            "scenario": scenario,
        },
        "modeled_device": {
            "name": "v5e-8" if dcn == 1 else f"{dcn}x-v5e-8",
            "chips": V5E8_CHIPS * dcn,
            "hbm_bytes_per_chip": int(hbm_bytes),
        },
        "audit_grid": mesh_shape,
        "fit_points": [
            {"peers": pt["peers"],
             "per_device_peak_bytes": (pt["memory"]["arguments"]
                                       + pt["memory"]["outputs"]
                                       + pt["memory"]["temp"]
                                       - pt["memory"]["aliased"])}
            for pt in points],
        "validation": {
            "peers": held["peers"],
            "predicted_per_device_bytes": int(predicted),
            "measured_per_device_bytes": int(measured_raw),
            "measured_after_aliasing_bytes": int(measured),
            "rel_err": round(rel_err, 6),
            "within_10pct": bool(rel_err <= 0.10),
        },
        "leaves": leaves_out,
        "predicted_per_device": {
            "arguments": int(arg_total),
            "outputs": mem_out["outputs"],
            "temp": mem_out["temp"],
            "total": total,
        },
        "hbm_utilization": round(utilization, 6),
        "verdict": "fits" if total <= hbm_bytes else "does-not-fit",
    }
