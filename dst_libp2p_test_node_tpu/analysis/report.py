"""Violation model + strict-JSON report for graft-audit.

Every finding — from the AST linter or the jaxpr auditor — is a Violation
with a stable rule id, a repo-relative file:line anchor, and (for jaxpr
rules) the registered entrypoint it was traced under. The report is strict
JSON (`allow_nan=False`, sorted keys, deterministic violation order) so CI
and the bench artifact pipeline can diff it byte-for-byte.

Rule catalog (see docs/ARCHITECTURE.md §10 for the long-form version):

  GA-J001  host/io/debug callback inside a scan/while_loop body
  GA-J002  x64 dtype or weak-type promotion drift in a loop carry
  GA-J003  declared lax.cond elided (vmapped cond lowered to select_n)
  GA-J004  declared buffer donation does not hold in the lowering
  GA-J005  compile-key count / feedback aval drift across the bench ladder
  GA-A001  np./math. call on a traced value inside a jitted scope
  GA-A002  float()/int()/bool() host coercion of a traced value
  GA-A003  Python `if`/`while`/ternary branching on a traced value
  GA-A004  device_get/block_until_ready/.item() host sync in a jitted scope
  GA-A005  json.dump without allow_nan=False or sanitize_nonfinite()
"""

from __future__ import annotations

import dataclasses
import json

SUPPRESS_COMMENT = "# graft-audit: ok"

RULES = {
    "GA-J001": "callback-in-loop",
    "GA-J002": "x64-or-weak-carry",
    "GA-J003": "cond-elided",
    "GA-J004": "donation-not-honored",
    "GA-J005": "compile-key-drift",
    "GA-A001": "np-math-on-tracer",
    "GA-A002": "host-coercion-of-tracer",
    "GA-A003": "python-branch-on-tracer",
    "GA-A004": "host-sync-in-traced-scope",
    "GA-A005": "nonfinite-reachable-json",
}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str              # GA-Jxxx / GA-Axxx id from RULES
    file: str              # repo-relative path (or module path for traces)
    line: int              # 1-based; 0 when no source anchor exists
    message: str
    entrypoint: str | None = None  # registry name for jaxpr-engine findings

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["slug"] = RULES.get(self.rule, "unknown")
        return d


def render_report(violations: list[Violation], *, checked_files: int = 0,
                  checked_entrypoints: int = 0) -> str:
    """Strict-JSON audit report; deterministic ordering, refuses NaN/Inf."""
    vs = sorted(violations, key=lambda v: (v.file, v.line, v.rule, v.message))
    counts: dict[str, int] = {}
    for v in vs:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    out = {
        "tool": "graft-audit",
        "version": 1,
        "clean": not vs,
        "checked_files": checked_files,
        "checked_entrypoints": checked_entrypoints,
        "counts": counts,
        "violations": [v.to_dict() for v in vs],
    }
    return json.dumps(out, indent=2, sort_keys=True, allow_nan=False)


def suppressed_lines(source: str) -> set[int]:
    """1-based line numbers carrying the in-line waiver comment."""
    return {
        i
        for i, text in enumerate(source.splitlines(), start=1)
        if SUPPRESS_COMMENT in text
    }
