"""Violation model + strict-JSON report for graft-audit.

Every finding — from the AST linter, the jaxpr auditor or the sharding
auditor — is a Violation with a stable rule id, a repo-relative file:line
anchor, and (for traced rules) the registered entrypoint it was traced
under. The report is strict JSON (`allow_nan=False`, sorted keys,
deterministic violation order) so CI and the bench artifact pipeline can
diff it byte-for-byte.

Rule catalog (see docs/LINT_RULES.md for the long-form version):

  GA-J001  host/io/debug callback inside a scan/while_loop body
  GA-J002  x64 dtype or weak-type promotion drift in a loop carry
  GA-J003  declared lax.cond elided (vmapped cond lowered to select_n)
  GA-J004  declared buffer donation does not hold in the lowering
  GA-J005  compile-key count / feedback aval drift across the bench ladder
  GA-A001  np./math. call on a traced value inside a jitted scope
  GA-A002  float()/int()/bool() host coercion of a traced value
  GA-A003  Python `if`/`while`/ternary branching on a traced value
  GA-A004  device_get/block_until_ready/.item() host sync in a jitted scope
  GA-A005  json.dump without allow_nan=False or sanitize_nonfinite()
  GA-S001  large operand replicated inside a sharded (multi-partition)
           contract
  GA-S002  collective kind in the compiled HLO not in the contract's
           declared `collectives` budget set
  GA-S003  summed per-device collective byte volume over the declared
           budget
  GA-S004  per-device peak memory over the declared HBM budget
  GA-S005  donation declared but not aliased in the COMPILED output
"""

from __future__ import annotations

import dataclasses
import json

SUPPRESS_COMMENT = "# graft-audit: ok"

RULES = {
    "GA-J001": "callback-in-loop",
    "GA-J002": "x64-or-weak-carry",
    "GA-J003": "cond-elided",
    "GA-J004": "donation-not-honored",
    "GA-J005": "compile-key-drift",
    "GA-A001": "np-math-on-tracer",
    "GA-A002": "host-coercion-of-tracer",
    "GA-A003": "python-branch-on-tracer",
    "GA-A004": "host-sync-in-traced-scope",
    "GA-A005": "nonfinite-reachable-json",
    "GA-S001": "replicated-large-operand",
    "GA-S002": "undeclared-collective",
    "GA-S003": "collective-bytes-over-budget",
    "GA-S004": "peak-memory-over-budget",
    "GA-S005": "donation-not-aliased-compiled",
}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str              # GA-Jxxx / GA-Axxx id from RULES
    file: str              # repo-relative path (or module path for traces)
    line: int              # 1-based; 0 when no source anchor exists
    message: str
    entrypoint: str | None = None  # registry name for jaxpr-engine findings

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["slug"] = RULES.get(self.rule, "unknown")
        return d


def render_report(violations: list[Violation], *, checked_files: int = 0,
                  checked_entrypoints: int = 0,
                  sharding: dict | None = None,
                  waived: list[dict] | None = None,
                  rung: dict | None = None) -> str:
    """Strict-JSON audit report; deterministic ordering, refuses NaN/Inf.

    Optional blocks (present only when the corresponding engine ran):
    `sharding` — per-contract GSPMD facts from the sharding auditor;
    `waived` — findings suppressed by a pinned contract waiver, each with
    its rationale; `rung` — the 1M-rung feasibility certificate."""
    vs = sorted(violations, key=lambda v: (v.file, v.line, v.rule, v.message))
    counts: dict[str, int] = {}
    for v in vs:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    out = {
        "tool": "graft-audit",
        "version": 1,
        "clean": not vs,
        "checked_files": checked_files,
        "checked_entrypoints": checked_entrypoints,
        "counts": counts,
        "violations": [v.to_dict() for v in vs],
    }
    if sharding is not None:
        out["sharding"] = sharding
    if waived is not None:
        out["waived"] = sorted(
            waived, key=lambda w: (w.get("entrypoint") or "", w.get("rule")
                                   or "", w.get("message") or ""))
    if rung is not None:
        out["rung_certificate"] = rung
    return json.dumps(out, indent=2, sort_keys=True, allow_nan=False)


def _gh_escape(text: str) -> str:
    """GitHub Actions workflow-command payload escaping."""
    return (text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A"))


def github_annotations(violations: list[Violation],
                       waived: list[dict] | None = None) -> list[str]:
    """`::error`/`::notice` workflow-command lines (`lint --format github`):
    one per finding, anchored at the violation's file:line so GA-* findings
    render inline on PRs. Waived findings come through as notices — visible
    on the diff, not failing the gate."""
    lines = []
    for v in sorted(violations,
                    key=lambda v: (v.file, v.line, v.rule, v.message)):
        who = f" [{v.entrypoint}]" if v.entrypoint else ""
        lines.append(
            f"::error file={_gh_escape(v.file)},line={max(v.line, 1)},"
            f"title={v.rule} {RULES.get(v.rule, 'unknown')}::"
            f"{_gh_escape(v.message + who)}")
    for w in waived or []:
        lines.append(
            f"::notice file={_gh_escape(w.get('file') or 'unknown')},"
            f"line={max(int(w.get('line') or 1), 1)},"
            f"title={w.get('rule')} waived::"
            f"{_gh_escape((w.get('message') or '') + ' — waiver: ' + (w.get('rationale') or ''))}")
    return lines


def suppressed_lines(source: str) -> set[int]:
    """1-based line numbers carrying the in-line waiver comment."""
    return {
        i
        for i, text in enumerate(source.splitlines(), start=1)
        if SUPPRESS_COMMENT in text
    }
