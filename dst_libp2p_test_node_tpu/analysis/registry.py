"""The hot-entrypoint contract registry.

Canonical small configs (N=32 single topic, T=2 x N=16 multitopic, 3-rung
aval-family miniature of the bench ladder) are built once per process and
shared across contracts — building them is pure numpy/host work plus a few
tiny device constants; the audit itself never executes a registered
entrypoint concretely (checkify mode excepted).

The registered surface mirrors the BENCH hot paths exactly:

  disseminate/cold        serialized-answer publish (2 surviving conds: the
                          exact-mode repair branch plus the nested
                          prefix-certificate fallback to the legacy serial
                          refiner)
  disseminate/warm        warm-started publish (3 surviving conds: repair +
                          certificate fallback + the cold-rerun guard)
  disseminate/exact_serial
                          the legacy serial refiner forced via
                          answer_queue_mode="serial" (1 surviving cond: the
                          repair branch only — no nested fallback to trace)
  disseminate/bounded     bounded-accounting publish (cond-free by design)
  publisher/batch_scan    the batched service dispatch (ISSUE 14): a scan
                          over stacked seed columns, disseminate/cold's 2
                          conds surviving in the body plus the padding
                          active-mask cond (3 total)
  heartbeat_step          one mesh-maintenance round (4 steady-state skips)
  run_heartbeats          the simulator scan step (conds must survive the
                          scan body)
  run_attacked_heartbeats the campaign attack window, UNBATCHED trial form
                          (the vmapped multi-seed form in runtime/campaign.py
                          intentionally trades these conds for select_n —
                          that form is deliberately NOT registered with a
                          cond contract; see docs/ARCHITECTURE.md §9)
  heartbeat_step/evict    the opt-in mesh-repair heartbeat (eviction +
                          PX-capture branches armed: 6 surviving conds)
  repair/recovery_window  the post-attack repair scan (ops/repair.py) with
                          the connection graph in the carry; checkified to
                          preserve the reverse-slot involution over the
                          mutated graph
  kad/find_node           the DHT lookup scan
  multitopic/disseminate  the T*N block-diagonal publish
  telemetry/recorded_heartbeats
                          the armed flight-recorder scan (ops/telemetry.py):
                          the heartbeat program plus the per-round channel
                          reductions riding the obs stack — the 4
                          steady-state conds must survive the added
                          instrumentation
  telemetry/recorded_attack_window
                          the attack window with the recorder armed via the
                          static telemetry kwarg — the UNBATCHED form, same
                          cond census as run_attacked_heartbeats
  campaign/attack_window_sharded
                          the LEGACY trial-only shard_map wrapper around
                          the vmapped attack window (nested=False): traced
                          on a device-count-adaptive 2-group trial mesh
                          with the repair leaves STRIPPED — retained as the
                          replicated-peer-submesh equality baseline (cond
                          census intentionally unset — the vmapped body
                          trades the heartbeat conds for select_n, see
                          run_attacked_heartbeats' note)
  campaign/attack_window_nested
                          the nested two-level pjit program the sharded
                          sweep dispatches by default: explicit
                          in/out_shardings over the full trials x peers
                          grid (2 groups x remaining devices per group),
                          peer rows partitioned inside each trial group
  campaign/faulted_window_nested
                          the fault-armed nested window: per-trial
                          crash/side/spike cohorts shard over both grid
                          axes like the attacker masks
  campaign/dht_attack_window
                          the cross-protocol recovery window
                          (ops/dht_adversary.py): repair armed, per-trial
                          poisoned discovery shortlists sharded over the
                          same nested grid and consumed by the redial path
  heartbeat/fused_round   the fused mega-round scan (ISSUE 16): one scan
                          over publish rounds, heartbeat burst + exact
                          publish in the body — all 6 phase conds survive
  native/score_update     the fused Pallas scoring-update kernel in
                          interpret mode (the jaxpr carries the real
                          pallas_call on every backend)
  episub/heartbeat_step   one episub tree round (ISSUE 19, ops/episub.py):
                          eager tree push + lazy IHAVE repair + graylisted
                          re-parenting, thresholds armed — exactly 1
                          surviving cond (the shared fmd/slow decay gate)
  protocol/arena_window   the arena's sharded episub attack window
                          (sharded_episub_window): nested trials x peers
                          grid like campaign/attack_window_nested, state
                          and ctrl feeding back aval-stable
"""

from __future__ import annotations

import functools

from .contracts import EntrypointContract, LadderRung, TraceSpec


@functools.lru_cache(maxsize=None)
def _single_topic(n: int = 32, connect_to: int = 4, **over):
    import jax.numpy as jnp

    from ..config.topology import Topology, TopoParams
    from ..ops.graph import build_connection_graph
    from ..ops.state import SimParams, graph_arrays, init_state

    g = build_connection_graph(n, connect_to, seed=0)
    params = SimParams(n=n, capacity=g.capacity, **dict(over))
    state = init_state(params, seed=0)
    a = graph_arrays(g)
    t = Topology.build(TopoParams(
        network_size=n, anchor_stages=5, min_bandwidth=50, max_bandwidth=150,
        min_latency=40, max_latency=130))
    topo = (jnp.asarray(t.stage_of_peer), jnp.asarray(t.latency_ms),
            jnp.asarray(t.bw_up_mbit))
    return g, params, state, a, topo


def _disseminate_spec(**params_over) -> TraceSpec:
    from ..ops.disseminate import disseminate

    g, params, state, a, (stage, lat, bw) = _single_topic(
        **{k: v for k, v in params_over.items()})
    return TraceSpec(
        fn=disseminate,
        args=(state, a["conns"], a["rev"], stage, lat, bw),
        kwargs=dict(publisher=3, t0_ms=0.0, params=params,
                    payload_bytes=15000))


def _publish_batch_spec() -> TraceSpec:
    import numpy as np

    from ..runtime.publisher import publish_batch_scan

    g, params, state, a, (stage, lat, bw) = _single_topic()
    rows = np.full(4, 3, dtype=np.int32)
    active = np.ones(4, dtype=bool)
    return TraceSpec(
        fn=publish_batch_scan,
        args=(state, a["conns"], a["rev"], stage, lat, bw, rows, active),
        kwargs=dict(t0_ms=0.0, params=params, payload_bytes=15000,
                    fragments=1, with_gossip=True, loss_stage=None,
                    loss_mode="tcp", lat_edge=None, loss_edge=None,
                    ans_tables=None, valid_edge=None, with_fanout=False))


def _heartbeat_spec(fn_name: str, **params_over) -> TraceSpec:
    from ..ops import heartbeat

    g, params, state, a, _ = _single_topic(**params_over)
    fn = getattr(heartbeat, fn_name)
    kwargs = {"params": params}
    if fn_name == "run_heartbeats":
        kwargs["steps"] = 4
    return TraceSpec(
        fn=fn, args=(state, a["conns"], a["rev"], a["out_mask"]),
        kwargs=kwargs)


# the armed-defense overrides every repair entrypoint traces under: the
# repair branches gate on scores, so auditing them against the default
# (thresholds compiled out) config would certify a path nobody runs
_ARMED = dict(slow_weight=-10.0, slow_decay=0.9, gossip_threshold=-10.0,
              publish_threshold=-20.0, graylist_threshold=-50.0)
_REPAIR = dict(evict=True, px=True, redial=True, **_ARMED)


def _repair_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import attacker_cohort
    from ..ops.repair import run_recovery_heartbeats

    g, params, state, a, _ = _single_topic(**_REPAIR)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    return TraceSpec(
        fn=run_recovery_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, steps=4, publisher=3))


def _attack_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import (AdversaryParams, attacker_cohort,
                                 run_attacked_heartbeats)

    g, params, state, a, _ = _single_topic()
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    return TraceSpec(
        fn=run_attacked_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, adv=AdversaryParams(), steps=4))


def _adaptive_attack_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import (AdaptivePolicy, AdversaryParams,
                                 attacker_cohort, run_adaptive_heartbeats)
    from ..ops.state import init_adaptive_ctrl

    # repair leaves live: the PX-poison behavior writes px_pool rows and the
    # audit should see that program, not the stripped fallback
    g, params, state, a, _ = _single_topic(**_REPAIR)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    adv = AdversaryParams(adaptive=AdaptivePolicy(enabled=True))
    return TraceSpec(
        fn=run_adaptive_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, adv=adv, steps=4,
                    ctrl=init_adaptive_ctrl(params.n)))


def _conform_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from .conformance import differential_round

    # the conformance harness's own fixture arming: thresholds live, repair
    # off — the program the differential walks per heartbeat
    g, params, state, a, _ = _single_topic(**_ARMED)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    return TraceSpec(
        fn=differential_round,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, adv=AdversaryParams(),
                    hb_idx=jnp.int32(0)))


def _faults_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.faults import FaultParams, fault_masks, run_faulted_heartbeats

    g, params, state, a, _ = _single_topic(**_ARMED)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    # every fault family armed at once: crash + partition + spike windows
    # overlapping, composed with an active adversary cohort — the maximal
    # program, so a cond lost in ANY family fails the audit
    faults = FaultParams(
        crash_frac=0.2, crash_window=(0, 2),
        partition_frac=0.3, partition_window=(1, 3),
        spike_frac=0.2, spike_window=(0, 4), spike_ms=250.0)
    fm = fault_masks(params.n, faults, seed=1, publisher=3)
    return TraceSpec(
        fn=run_faulted_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, adv=AdversaryParams(), faults=faults,
                    crash=jnp.asarray(fm["crash"]),
                    side=jnp.asarray(fm["side"]),
                    spike=jnp.asarray(fm["spike"]), steps=4))


def _sharded_attack_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.state import strip_repair
    from ..parallel.sharding import audit_trial_groups, make_trial_mesh
    from ..runtime.campaign import sharded_attack_window

    g, params, state, a, _ = _single_topic()
    # production path: params are repair-inert, so the campaign strips the
    # repair leaves host-side before stacking — trace the same program
    state, _saved = strip_repair(state)
    groups = audit_trial_groups()
    mesh = make_trial_mesh(groups, n_devices=groups)
    local = 2
    trials = groups * local
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.25, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_attack_window,
        args=(stacked, shared, att),
        kwargs=dict(params=params, adv=AdversaryParams(), steps=3,
                    trial_mesh=mesh, local_trials=local, nested=False))


def _nested_attack_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.state import strip_repair
    from ..parallel.sharding import audit_trial_groups, make_trial_mesh
    from ..runtime.campaign import sharded_attack_window

    g, params, state, a, _ = _single_topic()
    state, _saved = strip_repair(state)
    # the FULL grid: trial groups x every remaining device as each group's
    # peer submesh (2x2 under the CI lint gate's 4 virtual devices),
    # degenerating gracefully to 1x1 on a single device — the contract
    # always traces the nested pjit program the campaign dispatches,
    # whatever the host's device count. GRAFT_AUDIT_TRIAL_GROUPS flips the
    # grid aspect (2x4 vs 4x2 under CI's 8 devices) without a code change.
    groups = audit_trial_groups()
    mesh = make_trial_mesh(groups)
    local = 2
    trials = groups * local
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.25, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_attack_window,
        args=(stacked, shared, att),
        kwargs=dict(params=params, adv=AdversaryParams(), steps=3,
                    trial_mesh=mesh, local_trials=local))


def _dht_attack_window_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import attacker_cohort
    from ..ops.dht_adversary import (DhtAdversaryParams, build_attacked_dht,
                                     dht_repair_pool)
    from ..parallel.sharding import audit_trial_groups, make_trial_mesh
    from ..runtime.campaign import sharded_dht_recovery_window

    # repair ARMED (no strip_repair): the DHT window exists to feed the
    # redial path a poisoned shortlist, so the audited program is the one
    # with the repair leaves live in the carry
    g, params, state, a, (stage, lat, bw) = _single_topic(**_REPAIR)
    groups = audit_trial_groups()
    mesh = make_trial_mesh(groups)
    local = 2
    trials = groups * local
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    dht = DhtAdversaryParams(lookup_eclipse=True, warmup_waves=1,
                             lookup_rounds=2)
    atts, pools = [], []
    for s in range(trials):
        att_np = attacker_cohort(params.n, 0.25, seed=s)
        kstate, directory = build_attacked_dht(
            params.n, seed=s, dht=dht, attacker=att_np, victim=3,
            stage=stage, lat_ms=lat)
        pool, _ = dht_repair_pool(
            kstate, dht, stage, lat, attacker=jnp.asarray(att_np),
            directory=directory)
        atts.append(jnp.asarray(att_np))
        pools.append(pool)
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_dht_recovery_window,
        args=(stacked, shared, None, jnp.stack(atts), jnp.stack(pools)),
        kwargs=dict(rparams=params, steps=3, publisher=3, trial_mesh=mesh,
                    local_trials=local))


def _faulted_nested_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.faults import FaultParams, fault_masks
    from ..ops.state import strip_repair
    from ..parallel.sharding import audit_trial_groups, make_trial_mesh
    from ..runtime.campaign import sharded_faulted_window

    g, params, state, a, _ = _single_topic(**_ARMED)
    # production path: _ARMED leaves repair inert, so the campaign strips
    # the repair leaves host-side before stacking (runtime/campaign.py's
    # faulted dispatch) — trace that same program
    state, _saved = strip_repair(state)
    groups = audit_trial_groups()
    mesh = make_trial_mesh(groups)
    local = 2
    trials = groups * local
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    faults = FaultParams(
        crash_frac=0.2, crash_window=(0, 2),
        partition_frac=0.3, partition_window=(1, 3),
        spike_frac=0.2, spike_window=(0, 4), spike_ms=250.0)
    atts, crs, sds, sps = [], [], [], []
    for s in range(trials):
        atts.append(jnp.asarray(attacker_cohort(params.n, 0.25, seed=s)))
        fm = fault_masks(params.n, faults, seed=s, publisher=3)
        crs.append(jnp.asarray(fm["crash"]))
        sds.append(jnp.asarray(fm["side"]))
        sps.append(jnp.asarray(fm["spike"]))
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_faulted_window,
        args=(stacked, shared, jnp.stack(atts), jnp.stack(crs),
              jnp.stack(sds), jnp.stack(sps)),
        kwargs=dict(params=params, adv=AdversaryParams(), faults=faults,
                    steps=3, trial_mesh=mesh, local_trials=local))


def _episub_step_spec() -> TraceSpec:
    from ..ops.episub import (EpisubParams, episub_heartbeat_step,
                              init_episub_ctrl)

    # graylist thresholds live: the score-gated parent-eligibility edge
    # mask is a static compile-out under the reference defaults, and the
    # audited program must be the armed one the arena runs
    g, params, state, a, _ = _single_topic(**_ARMED)
    return TraceSpec(
        fn=episub_heartbeat_step,
        args=(state, init_episub_ctrl(params.n), a["conns"], a["rev"],
              a["out_mask"]),
        kwargs=dict(params=params, ep=EpisubParams(root=3)))


def _arena_window_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import (AdaptivePolicy, AdversaryParams,
                                 attacker_cohort)
    from ..ops.episub import EpisubParams, init_episub_ctrl
    from ..ops.state import strip_repair
    from ..parallel.sharding import audit_trial_groups, make_trial_mesh
    from ..runtime.campaign import sharded_episub_window

    # _ARMED is repair-inert: strip host-side exactly like _episub_windows
    g, params, state, a, _ = _single_topic(**_ARMED)
    state, _saved = strip_repair(state)
    groups = audit_trial_groups()
    mesh = make_trial_mesh(groups)
    local = 2
    trials = groups * local
    stack = lambda x: jnp.stack([jnp.asarray(x)] * trials)  # noqa: E731
    stacked = jax.tree_util.tree_map(stack, state)
    ctrls = jax.tree_util.tree_map(stack, init_episub_ctrl(params.n))
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.25, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    adv = AdversaryParams(adaptive=AdaptivePolicy(enabled=True))
    return TraceSpec(
        fn=sharded_episub_window,
        args=(stacked, ctrls, shared, att),
        kwargs=dict(params=params, ep=EpisubParams(root=3), adv=adv,
                    steps=3, trial_mesh=mesh, local_trials=local))


def attack_rung_spec(n: int, *, steps: int = 20, connect_to: int = 10,
                     local_trials: int = 2,
                     trial_groups: int | None = None) -> TraceSpec:
    """The 1M-rung ladder program at an arbitrary peer count: the nested
    attack window exactly as bench_configs config 8 dispatches it
    (scenario sybil_graft_flood, connect_to=10, fractions (0, 0.1) x seeds
    (0, 1) -> 2 trial groups x 2 local trials). The sharding auditor's
    rung predictor lowers THIS spec at 3-4 peer counts and extrapolates
    the per-leaf footprints to ATTACK_RUNG_PEERS on a modeled v5e-8."""
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.state import strip_repair
    from ..parallel.sharding import make_trial_mesh
    from ..runtime.campaign import sharded_attack_window

    g, params, state, a, _ = _single_topic(n=n, connect_to=connect_to)
    state, _saved = strip_repair(state)
    groups = 2 if trial_groups is None else trial_groups
    mesh = make_trial_mesh(groups)
    trials = groups * local_trials
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    # config 8's attacked fraction (the 0.0 baseline trials share the same
    # program — the mask content never changes the compiled footprint)
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.1, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_attack_window,
        args=(stacked, shared, att),
        kwargs=dict(params=params,
                    adv=AdversaryParams(scenario="sybil_graft_flood"),
                    steps=steps, trial_mesh=mesh,
                    local_trials=local_trials))


def _dcn_audit_shape() -> tuple[int, int]:
    """(dcn blocks, per-block trial groups) for the 3-level audit mesh,
    degrading with the host's device count the way audit_trial_groups
    does: 2x2x2 under the CI 8-device grid, 2x2x1 under the 4-device lint
    gate, 2x1x1 at two devices, 1x1x1 on a single device."""
    import jax

    nd = len(jax.devices())
    dcn = 2 if nd >= 2 else 1
    groups = 2 if nd // dcn >= 2 else 1
    return dcn, groups


def _dcn_block_devices() -> int:
    """Per-process device count on the canonical 3-level audit mesh — the
    GA-S006 blocking the contract declares (process-major device order
    makes partition_id // block the dcn index)."""
    import jax

    dcn, _groups = _dcn_audit_shape()
    return len(jax.devices()) // dcn


def _dcn_attack_window_spec() -> TraceSpec:
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import AdversaryParams, attacker_cohort
    from ..ops.state import strip_repair
    from ..parallel.sharding import make_dcn_mesh
    from ..runtime.campaign import sharded_attack_window

    # the three-level placement contract: the SAME nested window program the
    # campaign dispatches per process, traced single-process on the full
    # dcn x trials x peers mesh so GA-S006 can statically prove no
    # peer-axis collective ever crosses a dcn block boundary
    g, params, state, a, _ = _single_topic()
    state, _saved = strip_repair(state)
    dcn, groups = _dcn_audit_shape()
    mesh = make_dcn_mesh(dcn=dcn, trial_groups=groups)
    local = 2
    trials = dcn * groups * local
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.stack([jnp.asarray(x)] * trials), state)
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.25, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    return TraceSpec(
        fn=sharded_attack_window,
        args=(stacked, shared, att),
        kwargs=dict(params=params, adv=AdversaryParams(), steps=3,
                    trial_mesh=mesh, local_trials=local))


def arena_rung_spec(n: int, *, steps: int = 20, connect_to: int = 10,
                    local_trials: int = 2,
                    trial_groups: int | None = None) -> TraceSpec:
    """The arena ladder program at an arbitrary peer count: the sharded
    episub attack window (protocol/arena_window) on the config-8 grid
    shape, with the EpisubCtrl carry stacked alongside SimState. The rung
    predictor lowers THIS spec the same way it lowers attack_rung_spec, so
    the per-leaf power-law fits learn the `[...].hops/parent/reparents`
    leaves and the ROADMAP's arena-at-1M question gets the same
    compile-time fits / does-not-fit answer as the GossipSub window."""
    import jax
    import jax.numpy as jnp

    from ..ops.adversary import (AdaptivePolicy, AdversaryParams,
                                 attacker_cohort)
    from ..ops.episub import EpisubParams, init_episub_ctrl
    from ..ops.state import strip_repair
    from ..parallel.sharding import make_trial_mesh
    from ..runtime.campaign import sharded_episub_window

    g, params, state, a, _ = _single_topic(n=n, connect_to=connect_to,
                                           **_ARMED)
    state, _saved = strip_repair(state)
    groups = 2 if trial_groups is None else trial_groups
    mesh = make_trial_mesh(groups)
    trials = groups * local_trials
    stack = lambda x: jnp.stack([jnp.asarray(x)] * trials)  # noqa: E731
    stacked = jax.tree_util.tree_map(stack, state)
    ctrls = jax.tree_util.tree_map(stack, init_episub_ctrl(params.n))
    att = jnp.stack([
        jnp.asarray(attacker_cohort(params.n, 0.1, seed=s))
        for s in range(trials)])
    shared = {k: a[k] for k in ("conns", "rev", "out_mask")}
    adv = AdversaryParams(scenario="sybil_graft_flood",
                          adaptive=AdaptivePolicy(enabled=True))
    return TraceSpec(
        fn=sharded_episub_window,
        args=(stacked, ctrls, shared, att),
        kwargs=dict(params=params, ep=EpisubParams(root=3), adv=adv,
                    steps=steps, trial_mesh=mesh,
                    local_trials=local_trials))


def _telemetry_spec() -> TraceSpec:
    from ..ops.telemetry import TelemetryParams, run_recorded_heartbeats

    # armed score params so tel_graylisted_frac / tel_score_q exercise the
    # deferred-decay reconstruction against live thresholds
    g, params, state, a, _ = _single_topic(**_ARMED)
    return TraceSpec(
        fn=run_recorded_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"]),
        kwargs=dict(params=params, steps=4,
                    telemetry=TelemetryParams(record=True)))


def _telemetry_attack_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.adversary import (AdversaryParams, attacker_cohort,
                                 run_attacked_heartbeats)
    from ..ops.telemetry import TelemetryParams

    g, params, state, a, _ = _single_topic(**_ARMED)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    return TraceSpec(
        fn=run_attacked_heartbeats,
        args=(state, a["conns"], a["rev"], a["out_mask"], att),
        kwargs=dict(params=params, adv=AdversaryParams(), steps=4,
                    telemetry=TelemetryParams(record=True)))


def _fused_rounds_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops.disseminate import run_fused_rounds

    # fused_rounds=True arms the mega-round scan (the disabled path is
    # intentionally NOT registered here — it IS the phase-split chain's
    # cache entries, already audited above)
    g, params, state, a, (stage, lat, bw) = _single_topic(fused_rounds=True)
    return TraceSpec(
        fn=run_fused_rounds,
        args=(state, a["conns"], a["rev"], stage, lat, bw, a["out_mask"],
              jnp.arange(3, 6, dtype=jnp.int32)),
        kwargs=dict(params=params, payload_bytes=15000, hb_per_round=2))


@functools.lru_cache(maxsize=None)
def _score_update_fn(params):
    """One shared jitted wrapper per params: contract builds must return
    the SAME callable so the second measure_retraces call is a pure cache
    hit (a per-build closure would retrace by construction)."""
    import jax

    from ..native.score_update import score_update

    return jax.jit(functools.partial(score_update, params=params,
                                     interpret=True))


def _score_update_spec() -> TraceSpec:
    import jax.numpy as jnp

    g, params, state, a, _ = _single_topic(slow_weight=-10.0)
    n, c = params.n, params.capacity
    fmd = (jnp.arange(n * c, dtype=jnp.float32).reshape(n, c) % 13) * 0.3
    slow = (jnp.arange(n * c, dtype=jnp.float32).reshape(n, c) % 7) * 0.2
    return TraceSpec(
        fn=_score_update_fn(params),
        args=(fmd, slow, 0.9, 0.8))


def _kad_spec() -> TraceSpec:
    import jax.numpy as jnp

    from ..ops import kad

    g, params, state, a, (stage, lat, bw) = _single_topic()
    st = kad.init_kad_state(params.n, seed=0)
    origins = jnp.arange(4, dtype=jnp.int32)
    return TraceSpec(
        fn=kad.find_node,
        args=(st, origins, st.keys[origins], stage, lat),
        kwargs=dict(rounds=3))


@functools.lru_cache(maxsize=None)
def _multitopic_sim():
    from ..config.topology import TopoParams
    from ..runtime.multitopic import MultiTopicConfig, MultiTopicSimulator

    cfg = MultiTopicConfig(
        topo=TopoParams(network_size=16, anchor_stages=1),
        topics=("a", "b"), connect_to=3)
    return MultiTopicSimulator(cfg)


def _multitopic_spec() -> TraceSpec:
    from ..ops.disseminate import disseminate

    sim = _multitopic_sim()
    return TraceSpec(
        fn=disseminate,
        args=(sim.state, sim.arrays["conns"], sim.arrays["rev"], sim._stage,
              sim._lat, sim._bw),
        kwargs=dict(publisher=16 + 3, t0_ms=0.0, params=sim.params,
                    payload_bytes=500, lat_edge=sim._lat_edge,
                    ans_tables=sim._ans_tables))


def _disseminate_ladder() -> list[LadderRung]:
    """Miniature of the bench ladder's aval families: three network sizes
    plus a REPEAT of the first — 4 rungs must produce exactly 3 compile
    keys (distinct sizes split, identical configs collapse)."""
    rungs = []
    for name, n, ct in (("rung-16", 16, 3), ("rung-32", 32, 4),
                        ("rung-64", 64, 5), ("rung-16-again", 16, 3)):
        g, params, state, a, (stage, lat, bw) = _single_topic(
            n=n, connect_to=ct)
        rungs.append(LadderRung(
            name=name, statics=(params, 15000),
            dynamic=(state, a["conns"], a["rev"], stage, lat, bw, 3, 0.0)))
    return rungs


def _new_state_of(out):
    return out[1]


def _state_arg_of(spec):
    return spec.args[0]


def _first_out(out):
    return out[0]


def _checkify_heartbeat() -> None:
    """Runtime half of the heartbeat contract: from the canonical warm mesh,
    one scan keeps D_lo <= |mesh| <= D_hi for every live peer."""
    import jax.numpy as jnp
    from jax.experimental import checkify

    from ..ops.heartbeat import run_heartbeats

    g, params, state, a, _ = _single_topic()

    def prog(state):
        s = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                           params, 8)
        deg = s.mesh_mask.sum(axis=-1)
        checkify.check(
            jnp.all((deg >= params.d_low) & (deg <= params.d_high)),
            "mesh degree left [D_lo, D_hi]")
        checkify.check(
            jnp.all(s.fmd >= 0.0), "score decay went negative")
        return s

    err, _ = checkify.checkify(prog)(state)
    err.throw()


def _checkify_repair() -> None:
    """Runtime half of the recovery contract: after a repair window the
    reverse-slot involution still holds over the MUTATED graph — every
    committed dial extended conns/rev consistently on both sides — and the
    repair counters are consistent (a PX graft is a graft)."""
    import jax.numpy as jnp
    from jax.experimental import checkify

    from ..ops.adversary import attacker_cohort
    from ..ops.heartbeat import run_heartbeats
    from ..ops.repair import run_recovery_heartbeats

    g, params, state, a, _ = _single_topic(**_REPAIR)
    state = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                           params, 8)
    att = jnp.asarray(attacker_cohort(params.n, 0.25, seed=1))
    # force repair activity: pre-starve by evicting the attacker edges via
    # a hostile penalty so the dial path actually runs under the check
    state = state.replace(slow_penalty=jnp.where(
        att[jnp.clip(a["conns"], 0)] & (a["conns"] >= 0),
        jnp.float32(100.0), state.slow_penalty))
    (s2, cn, rv, om), _obs = run_recovery_heartbeats(
        state, a["conns"], a["rev"], a["out_mask"], att, params,
        steps=8, publisher=3)

    def prog(cn, rv, px_grafts, redials, grafts0, grafts1):
        me = jnp.arange(cn.shape[0], dtype=cn.dtype)[:, None]
        back = cn[jnp.clip(cn, 0), rv]
        checkify.check(
            jnp.all(jnp.where(cn >= 0, back == me, True)),
            "reverse-slot involution broken after repair window")
        checkify.check(
            jnp.all(rv >= 0) & jnp.all(rv < cn.shape[1]),
            "rev slot out of range after repair window")
        checkify.check(
            (px_grafts + redials).sum() <= (grafts1 - grafts0).sum() * 2 + 1,
            "repair counters inconsistent with graft accounting")
        return cn

    err, _ = checkify.checkify(prog)(
        cn, rv, s2.px_grafts, s2.redials, state.grafts, s2.grafts)
    err.throw()


def _checkify_disseminate() -> None:
    """Runtime half of the publish contract: delays are non-negative where
    received, and the bounded-mode wait bar is finite (json-safe)."""
    import jax.numpy as jnp
    from jax.experimental import checkify

    from ..ops.disseminate import disseminate
    from ..ops.heartbeat import run_heartbeats

    g, params, state, a, (stage, lat, bw) = _single_topic()
    state = run_heartbeats(state, a["conns"], a["rev"], a["out_mask"],
                           params, 8)

    # checkify cannot trace the fixpoint's batched while-loop
    # (checkify-of-vmap-of-while is unsupported), so run the publish
    # concretely and checkify only the assertions over its outputs.
    res, _s2 = disseminate(
        state, a["conns"], a["rev"], stage, lat, bw, publisher=3,
        t0_ms=0.0, params=params, payload_bytes=15000)

    def prog(received, delay_ms, answer_wait_max_ms):
        checkify.check(
            jnp.all(jnp.where(received, delay_ms, 0.0) >= 0.0),
            "negative dissemination delay")
        checkify.check(
            jnp.isfinite(answer_wait_max_ms),
            "non-finite answer wait bar (would poison strict JSON)")
        return received

    err, _ = checkify.checkify(prog)(
        res.received, res.delay_ms, res.answer_wait_max_ms)
    err.throw()


def default_contracts() -> list[EntrypointContract]:
    return [
        EntrypointContract(
            name="disseminate/cold",
            build=lambda: _disseminate_spec(),
            expected_conds=2,
            donate=(0,),
            ladder=_disseminate_ladder,
            expected_compile_keys=3,
            feedback=[(_new_state_of, _state_arg_of)],
            runtime_check=_checkify_disseminate,
            notes="serialized-answer repair branch must stay a real cond, "
                  "and the prefix-certificate fallback to the legacy serial "
                  "refiner must stay a NESTED cond inside it (the untaken "
                  "serial branch costs compile only — converting either to "
                  "select_n would run the serial refiner on every publish)"),
        EntrypointContract(
            name="disseminate/warm",
            build=lambda: _disseminate_spec(warm_start=True),
            expected_conds=3,
            feedback=[(_new_state_of, _state_arg_of)],
            notes="repair + certificate fallback + cold-rerun guard all "
                  "survive"),
        EntrypointContract(
            name="disseminate/exact_serial",
            build=lambda: _disseminate_spec(answer_queue_mode="serial"),
            expected_conds=1,
            feedback=[(_new_state_of, _state_arg_of)],
            notes="the legacy serial refiner forced by static param — the "
                  "bit-equality reference the prefix engine is pinned "
                  "against (tests/test_exact_prefix.py); only the repair "
                  "branch survives, there is no nested fallback to trace"),
        EntrypointContract(
            name="disseminate/bounded",
            build=lambda: _disseminate_spec(serialize_answers=False),
            expected_conds=None,
            feedback=[(_new_state_of, _state_arg_of)],
            notes="cond-free by design; loop/carry rules still apply"),
        EntrypointContract(
            name="publisher/batch_scan",
            build=_publish_batch_spec,
            expected_conds=3,
            feedback=[(_new_state_of, _state_arg_of)],
            notes="the batched service dispatch (ISSUE 14): one scan over "
                  "stacked seed columns whose body is disseminate/cold — "
                  "its 2 conds must survive inside the scan body, plus the "
                  "per-column active-mask cond that makes padding to a "
                  "static batch width free (a select_n there would publish "
                  "the padding columns); the carried SimState must feed "
                  "back aval-stable so every pump round is a cache hit"),
        EntrypointContract(
            name="heartbeat_step",
            build=lambda: _heartbeat_spec("heartbeat_step"),
            expected_conds=4,
            donate=(0,),
            notes="graft/prune/fanout/deg skips are the steady-state perf"),
        EntrypointContract(
            name="run_heartbeats",
            build=lambda: _heartbeat_spec("run_heartbeats"),
            expected_conds=4,
            donate=(0,),
            feedback=[(lambda out: out, _state_arg_of)],
            runtime_check=_checkify_heartbeat,
            notes="the simulator scan step; conds live inside the scan body"),
        EntrypointContract(
            name="run_attacked_heartbeats",
            build=_attack_spec,
            expected_conds=4,
            feedback=[(_first_out, _state_arg_of)],
            notes="UNBATCHED campaign window; the vmapped trial batch "
                  "intentionally elides these conds and is not registered"),
        EntrypointContract(
            name="adversary/adaptive_window",
            build=_adaptive_attack_spec,
            expected_conds=None,
            # the armed window widens the carry to (state, ctrl): BOTH feed
            # the next window — the controller estimate crosses the
            # attack -> recovery edge, so aval drift in either leaf
            # recompiles every campaign window
            feedback=[(lambda out: out[0][0], _state_arg_of),
                      (lambda out: out[0][1],
                       lambda spec: spec.kwargs["ctrl"])],
            # single-device program: any collective appearing in its
            # compiled HLO means a mesh leaked into the unbatched window
            collectives=frozenset(),
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the adaptive attacker controller in the scan (ISSUE 15): "
                  "repair leaves live so PX poison writes real px_pool "
                  "rows; disabled configs are intentionally NOT registered "
                  "here — they ARE run_attacked_heartbeats (same cache "
                  "entry), already audited above"),
        EntrypointContract(
            name="heartbeat_step/evict",
            build=lambda: _heartbeat_spec("heartbeat_step", **_REPAIR),
            expected_conds=6,
            donate=(0,),
            notes="opt-in repair branches: the 4 default skips plus the "
                  "eviction and PX-capture conds must SURVIVE (a select_n "
                  "here would pay both branches in the steady state)"),
        EntrypointContract(
            name="repair/recovery_window",
            build=_repair_spec,
            expected_conds=7,
            # the WHOLE carry feeds back: (state, conns, rev, out_mask) —
            # the dynamic graph is a loop-carried value, not a constant
            feedback=[(_first_out, lambda spec: spec.args[:4])],
            runtime_check=_checkify_repair,
            notes="recovery scan: 6 armed-heartbeat conds + the repair "
                  "controller's single action cond, all inside the scan "
                  "body; the graph arrays ride the carry"),
        EntrypointContract(
            name="faults/churn_window",
            build=_faults_spec,
            expected_conds=None,
            feedback=[(_first_out, _state_arg_of)],
            # the UNBATCHED single-device window: collective-free by
            # construction
            collectives=frozenset(),
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="fault window with crash + partition + spike all armed "
                  "over an attacked mesh: the go-dark/restart and "
                  "freeze/thaw branches are window-scheduled lax.conds "
                  "inside the scan; state must feed back aval-stable so "
                  "retried trials resume from a checkpoint without a "
                  "recompile"),
        EntrypointContract(
            name="campaign/faulted_window_nested",
            build=_faulted_nested_spec,
            expected_conds=None,
            feedback=[(_first_out, _state_arg_of)],
            # explicit in/out_shardings force a fresh jit closure per
            # window: one compile per call by construction
            retrace_budget=1,
            # ~18 KiB/device measured at the audit shape: the fault masks
            # ride the same gathers as the attacker masks
            collectives=frozenset(
                {"all-gather", "all-reduce", "collective-permute"}),
            collective_bytes_budget=72 * 1024,
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the fault-armed nested window (sharded_faulted_window): "
                  "per-trial crash/side/spike cohorts shard over both grid "
                  "axes exactly like the attacker masks, so fault sweeps "
                  "ride the trials x peers grid instead of falling back to "
                  "the vmapped single-device stack; repair leaves stripped "
                  "(the _ARMED params are repair-inert, matching the "
                  "campaign's host-side strip), and the sharding auditor "
                  "pins the same collective-kind set as the attack window"),
        EntrypointContract(
            name="campaign/attack_window_sharded",
            build=_sharded_attack_spec,
            expected_conds=None,
            feedback=[(_first_out, _state_arg_of)],
            # the wrapper jits a fresh shard_map closure per call — one
            # compile per window by construction, never more
            retrace_budget=1,
            # trials are independent on the trial-only grid: no cross-
            # device traffic is ever legitimate in this program
            collectives=frozenset(),
            hbm_budget_bytes=2 * 1024 * 1024,
            # GA-S001 fires by design here: the legacy layout REPLICATES
            # the epoch graph across the trial groups (that is what makes
            # it the replicated-peer-submesh baseline the nested program
            # is measured against) — pinned, not fixed
            waivers=(("GA-S001",
                      "legacy nested=False layout replicates the shared "
                      "epoch graph (conns/rev) across trial groups by "
                      "design — it exists as the replicated-peer-submesh "
                      "equality baseline for the nested program "
                      "(docs/ARCHITECTURE.md §13)"),),
            notes="legacy trial-only shard_map (nested=False), repair "
                  "leaves stripped — the replicated-peer-submesh baseline "
                  "the nested program is pinned against; the stacked state "
                  "must feed back aval-stable across windows, and "
                  "loop/carry rules catch dead weight the r05 way"),
        EntrypointContract(
            name="campaign/attack_window_nested",
            build=_nested_attack_spec,
            expected_conds=None,
            feedback=[(_first_out, _state_arg_of)],
            # explicit in/out_shardings force a fresh jit closure per
            # window: one compile per call by construction
            retrace_budget=1,
            # measured at the canonical audit shape (N=32, 8 devices):
            # ~16 KiB/device of collective output across the three kinds
            # the neighbor gathers + trial reductions legitimately insert;
            # budgets are ~4x ratchets, not estimates
            collectives=frozenset(
                {"all-gather", "all-reduce", "collective-permute"}),
            collective_bytes_budget=64 * 1024,
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the nested two-level pjit program the sharded sweep "
                  "actually dispatches: trials split over groups, peer "
                  "rows split over each group's submesh via explicit "
                  "in/out_shardings; same aval-stability and loop/carry "
                  "bars as the legacy baseline; the sharding auditor "
                  "additionally pins its collective kinds and byte/HBM "
                  "budgets (GA-S002..4) — a reduce-scatter or all-to-all "
                  "appearing here means the partitioner stopped seeing "
                  "the layout the grid was designed around"),
        EntrypointContract(
            name="campaign/attack_window_dcn",
            build=_dcn_attack_window_spec,
            expected_conds=None,
            feedback=[(_first_out, _state_arg_of)],
            # explicit in/out_shardings force a fresh jit closure per
            # window: one compile per call by construction
            retrace_budget=1,
            collectives=frozenset(
                {"all-gather", "all-reduce", "collective-permute"}),
            collective_bytes_budget=64 * 1024,
            hbm_budget_bytes=2 * 1024 * 1024,
            # GA-S006: on the 3-level mesh a dcn block is one process's
            # devices — device_count / dcn with make_dcn_mesh's defaults —
            # and the cross-DCN byte budget is literally zero: trials are
            # embarrassingly parallel across processes, every peer-axis
            # collective must stay inside one ICI block
            dcn_block_devices=_dcn_block_devices(),
            dcn_collective_bytes_budget=0,
            notes="the multi-host placement contract (ISSUE 20): the same "
                  "nested attack window traced on the three-level "
                  "dcn x trials x peers mesh, stacked trials split "
                  "(dcn, trials)-major and peer rows over each block's "
                  "submesh. GA-S006 parses every collective's replica "
                  "groups and proves zero bytes cross the dcn axis — the "
                  "static license for run_campaign(dcn=...) to execute "
                  "per-process on local submeshes (supervisor retries, "
                  "checkpoints, recovery all process-local) without "
                  "losing anything the global formulation would compute"),
        EntrypointContract(
            name="campaign/dht_attack_window",
            build=_dht_attack_window_spec,
            expected_conds=None,
            # the carry is (state, conns, rev, out_mask, pool): the state
            # feeds the next window's state slot and the consumed pool the
            # pool slot (the heal leg over stacked graphs is a separate
            # call form, not this entrypoint's feedback)
            feedback=[(lambda out: out[0][0], _state_arg_of),
                      (lambda out: out[0][4], lambda spec: spec.args[4])],
            # explicit in/out_shardings force a fresh jit closure per
            # window: one compile per call by construction (the second
            # heal leg traces its OWN closure over stacked graphs — a
            # separate entrypoint, not a retrace of this one)
            retrace_budget=1,
            # ~23 KiB/device measured at the audit shape: the redial path
            # gathers the poisoned (T, N, K) shortlists on top of the
            # attack window's own collectives
            collectives=frozenset(
                {"all-gather", "all-reduce", "collective-permute"}),
            collective_bytes_budget=96 * 1024,
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the cross-protocol recovery window: repair leaves LIVE "
                  "(the poisoned shortlist feeds the redial path), the "
                  "(T, N, K) discovery pools shard over both grid axes and "
                  "ride the scan carry; aval-stability across windows is "
                  "the bar — the heal leg must reuse the same program "
                  "shape with only the pool contents changed"),
        EntrypointContract(
            name="telemetry/recorded_heartbeats",
            build=_telemetry_spec,
            expected_conds=4,
            feedback=[(_first_out, _state_arg_of)],
            notes="flight recorder armed: the channel reductions ride the "
                  "obs stack without converting any steady-state skip to "
                  "select_n; state feeds back aval-stable so windowed "
                  "recording never recompiles"),
        EntrypointContract(
            name="telemetry/recorded_attack_window",
            build=_telemetry_attack_spec,
            expected_conds=4,
            feedback=[(_first_out, _state_arg_of)],
            notes="attack window with the recorder armed via the static "
                  "telemetry kwarg — same cond census as the bare window; "
                  "the tel_* channels are pure reductions"),
        EntrypointContract(
            name="heartbeat/fused_round",
            build=_fused_rounds_spec,
            expected_conds=6,
            feedback=[(_first_out, _state_arg_of)],
            notes="the fused mega-round scan (ISSUE 16, ARCHITECTURE §18): "
                  "one lax.scan over publish rounds whose body is the "
                  "heartbeat burst + the exact publish — run_heartbeats' 4 "
                  "steady-state skips plus disseminate/cold's 2 conds "
                  "(repair + serial-certificate fallback) must all survive "
                  "INSIDE the fused scan body; the returned state feeds the "
                  "next call aval-stable, and the whole chain must stay one "
                  "cache entry per shape (the disabled path literally IS "
                  "the phase-split chain and is audited via its own "
                  "contracts)"),
        EntrypointContract(
            name="native/score_update",
            build=_score_update_spec,
            expected_conds=None,
            feedback=[(lambda out: out[0], lambda spec: spec.args[0]),
                      (lambda out: out[1], lambda spec: spec.args[1])],
            notes="the fused Pallas scoring-update kernel "
                  "(native/score_update.py), traced in interpret mode so "
                  "the audited jaxpr contains the real pallas_call on any "
                  "backend; the decayed counters feed back aval-stable "
                  "(they are the next round's inputs), and the XLA "
                  "reference score_update_xla is the correctness target: "
                  "counters bitwise, score to ulp-level FMA tolerance "
                  "(tests/test_score_kernel.py)"),
        EntrypointContract(
            name="kad/find_node",
            build=_kad_spec,
            feedback=[(lambda out: out[1], _state_arg_of)],
            notes="lookup scan: loop/carry rules only"),
        EntrypointContract(
            name="multitopic/disseminate",
            build=_multitopic_spec,
            expected_conds=2,
            feedback=[(_new_state_of, _state_arg_of)],
            notes="T*N block-diagonal stack keeps the single-topic conds"),
        EntrypointContract(
            name="conformance/differential_round",
            build=_conform_spec,
            expected_conds=4,
            feedback=[(lambda out: out, _state_arg_of)],
            notes="the compiled side of the spec-differential gate "
                  "(analysis/conformance.py): one heartbeat_step -> "
                  "adversary_round composition per round, audited here so "
                  "the program the conformance oracle certifies is the "
                  "same steady-state-skip program the runners scan (the 4 "
                  "heartbeat conds must survive; the returned state feeds "
                  "the next round aval-stable)"),
        EntrypointContract(
            name="episub/heartbeat_step",
            build=_episub_step_spec,
            expected_conds=1,
            feedback=[(lambda out: out[0], lambda spec: spec.args[0]),
                      (lambda out: out[1], lambda spec: spec.args[1])],
            collectives=frozenset(),
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the episub tree round (ops/episub.py, ARCHITECTURE §21): "
                  "eager push down the spanning tree + lazy IHAVE repair on "
                  "non-tree edges + graylist-gated re-parenting, all dense "
                  "masked ops — exactly one cond survives (the fmd/slow "
                  "decay gate shared with gossipsub's scorer); state and "
                  "ctrl both feed back aval-stable, and single-device "
                  "tracing must stay collective-free"),
        EntrypointContract(
            name="protocol/arena_window",
            build=_arena_window_spec,
            expected_conds=None,
            feedback=[(lambda out: out[0][0], lambda spec: spec.args[0]),
                      (lambda out: out[0][1], lambda spec: spec.args[1])],
            retrace_budget=1,
            collectives=frozenset({"all-gather", "all-reduce",
                                   "collective-permute"}),
            collective_bytes_budget=64 * 1024,
            hbm_budget_bytes=2 * 1024 * 1024,
            notes="the arena's sharded episub attack window "
                  "(runtime/campaign.py sharded_episub_window), nested "
                  "trial x group sharding like campaign/attack_window_"
                  "nested; ISSUE 19's 'retrace budget 0' reads as zero "
                  "EXTRA retraces — explicit in/out_shardings force one "
                  "fresh jit closure per window, the house budget for "
                  "every nested window (retrace_budget=1); state and ctrl "
                  "feed back aval-stable (actrl is window-internal, no "
                  "input slot), and per-trial collective traffic stays "
                  "under the attack-window byte budget"),
    ]
