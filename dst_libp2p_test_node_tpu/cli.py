"""CLI driver: the `SIMBACKEND=tpu` replacement for shadow/run.sh + topogen.py.

Subcommands:

  topogen    — emit network_topology.gml + shadow.yaml. Accepts BOTH the
               reference topogen's argparse flags (-n/-bl/-bh/...) and the 13
               positional args shadow/run.sh actually passes (the reference's
               two halves are out of sync — run.sh:49-50 sends positionals to
               a flags-only parser; we accept either, SURVEY.md §7 quirks).
  run        — the 14-positional-arg experiment driver mirroring
               shadow/run.sh:23-38: generates the topology, runs the JAX
               simulation N times, writes awk-compatible latencies<i> files
               and prints the per-run summaries (small/large switch at
               msg_size < 1000, run.sh:68-72).
  summarize  — re-run the summary over an existing latencies file.
  serve      — long-lived node service (HTTP /publish + /health, Prometheus).
  inject     — publisher controller: POST /publish to node services at a
               fixed inter-message delay (pod-api-requester / traffic_sync.py
               analog, shadow/Dockerfile:45-53, topogen.py:124-136).
  attack     — adversarial Monte-Carlo campaign (runtime/campaign.py): sweep
               attacker fraction x seed for one of the v1.1 attack scenarios
               (ops/adversary.py, arXiv:2007.02754) and report resilience
               metrics against the score defense. --adaptive arms the
               per-round attacker controller inside the heartbeat scan.
  pareto     — defense Pareto sweep (runtime/campaign.run_defense_sweep):
               grid over mesh-degree/scoring knobs vs the adaptive attacker,
               report the coverage/bandwidth/recovery-time front and which
               configurations dominate the defaults.
  arena      — protocol arena (runtime/campaign.run_arena_campaign):
               GossipSub vs episub (ops/episub.py, Topiary-style tree) on
               identical graphs/traffic/fault cohorts under the same
               adaptive attacker; strict-JSON head-to-head artifact with
               the per-scenario win matrix.
  kad        — role-based kad-dht workload (bootstrap/normal/probe).
  connmanager — hub-and-spoke watermark/reconnect stress workload.
  servicedisco — advertise/lookup service discovery over the DHT.
  regression — GossipSub-over-kad-dht discovery workload with mesh pings.
  lint       — graft-audit static certification: AST lint over the python
               surface + jaxpr audit of every registered hot entrypoint
               (analysis/). Strict-JSON report on stdout, exit 0 iff clean.
  conform    — conformance oracle (analysis/conformance.py): differential-
               test the compiled heartbeat/adversary step against the
               pure-numpy GossipSub v1.1 reference model (ops/spec.py,
               ACL2s transcription) over the attack canon and emit a
               strict-JSON certificate. Unwaivered divergence = exit 1
               (waiver table: docs/CONFORMANCE.md).
  trace      — flight-recorder export (ops/telemetry.py): run a warmup plus
               a recorded heartbeat window and emit a Chrome-trace/perfetto
               JSON timeline, a per-round .npz and a CSV of every tel_*
               channel; --profile-dir additionally captures a jax.profiler
               trace around the run.
  microbench — per-kernel roofline + Pallas block-size autotune harness
               (runtime/microbench.py): measured walls + XLA cost analyses
               over the entrypoint-contract registry, an explicit row-block
               sweep over the native/ kernels (--install writes the winning
               tuned.json), and the packed_state A/B verdict. Strict-JSON
               artifact on stdout or --out.

Usage:
  python -m dst_libp2p_test_node_tpu run 1 1000 15000 1 10 50 150 40 130 5 0.0 4 0 4000
  python -m dst_libp2p_test_node_tpu topogen -n 100 -st 5 -bl 50 -bh 150
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .config.env import env_str, gossipsub_params_from_env
from .config.topology import Topology, TopoParams

# run.sh positional order (run.sh:23-38)
RUN_SH_PARAMS = [
    "runs", "nodes", "msg_size", "num_frag", "num_publishers",
    "min_bandwidth", "max_bandwidth", "min_latency", "max_latency",
    "anchor_stages", "packet_loss", "publisher_id", "publisher_rotation",
    "inter_message_delay_ms",
]
# the 13 positionals run.sh hands to topogen (run.sh:49-50), in its order
TOPOGEN_POSITIONALS = [
    "nodes", "min_bandwidth", "max_bandwidth", "min_latency", "max_latency",
    "anchor_stages", "packet_loss", "msg_size", "num_frag", "num_publishers",
    "publisher_id", "publisher_rotation", "inter_message_delay_ms",
]


def _topo_flags(p: argparse.ArgumentParser) -> None:
    """The reference topogen's flag surface (topogen.py:13-36)."""
    p.add_argument("-n", "--network-size", type=int, default=100)
    p.add_argument("-bl", "--min-bandwidth", type=int, default=50)
    p.add_argument("-bh", "--max-bandwidth", type=int, default=50)
    p.add_argument("-ll", "--min-latency", type=int, default=100)
    p.add_argument("-lh", "--max-latency", type=int, default=100)
    p.add_argument("-st", "--anchor-stages", type=int, default=1)
    p.add_argument("-l", "--packet-loss", type=float, default=0.0)
    p.add_argument("-s", "--msg-size-bytes", type=int, default=1500)
    p.add_argument("-f", "--num-frags", type=int, choices=range(1, 10), default=1)
    p.add_argument("-m", "--messages", type=int, default=10)
    p.add_argument("-d", "--delay-seconds", type=float, default=0.1)
    p.add_argument(
        "-mx", "--muxer", choices=["mplex", "yamux", "quic"], default="yamux"
    )


def _params_from_flags(a) -> TopoParams:
    return TopoParams(
        network_size=a.network_size,
        min_bandwidth=a.min_bandwidth,
        max_bandwidth=a.max_bandwidth,
        min_latency=a.min_latency,
        max_latency=a.max_latency,
        anchor_stages=a.anchor_stages,
        packet_loss=a.packet_loss,
        msg_size_bytes=a.msg_size_bytes,
        num_frags=a.num_frags,
        messages=a.messages,
        delay_seconds=a.delay_seconds,
        muxer=a.muxer,
    )


def _topo_from_fields(m: dict, muxer: str = "yamux") -> TopoParams:
    """One place owns the run.sh-field -> TopoParams contract (both the
    `topogen` positional form and the `run` driver feed through here)."""
    return TopoParams(
        network_size=int(m["nodes"]),
        min_bandwidth=int(m["min_bandwidth"]),
        max_bandwidth=int(m["max_bandwidth"]),
        min_latency=int(m["min_latency"]),
        max_latency=int(m["max_latency"]),
        anchor_stages=int(m["anchor_stages"]),
        packet_loss=float(m["packet_loss"]),
        msg_size_bytes=int(m["msg_size"]),
        num_frags=int(m["num_frag"]),
        messages=int(m["num_publishers"]),
        delay_seconds=float(m["inter_message_delay_ms"]) / 1000.0,
        muxer=muxer,
    )


def _params_from_positionals(vals: list[str]) -> tuple[TopoParams, dict]:
    m = dict(zip(TOPOGEN_POSITIONALS, vals))
    extra = {
        "publisher_id": int(m["publisher_id"]),
        "publisher_rotation": bool(int(m["publisher_rotation"])),
    }
    return _topo_from_fields(m), extra


def cmd_topogen(argv: list[str]) -> int:
    if argv and not argv[0].startswith("-"):
        if len(argv) != 13:
            print(
                f"topogen: expected 13 positional args ({' '.join(TOPOGEN_POSITIONALS)}) "
                f"or flag form, got {len(argv)}",
                file=sys.stderr,
            )
            return 2
        topo, _ = _params_from_positionals(argv)
    else:
        p = argparse.ArgumentParser(prog="topogen")
        _topo_flags(p)
        topo = _params_from_flags(p.parse_args(argv))
    t = Topology.build(topo)
    t.write_gml()
    t.write_shadow_yaml()
    print(f"wrote network_topology.gml + shadow.yaml ({topo.network_size} peers, "
          f"{topo.anchor_stages} stages)")
    return 0


def cmd_run(argv: list[str]) -> int:
    # flags appended after the 14 positionals tune the TPU backend
    p = argparse.ArgumentParser(
        prog="run",
        usage="run <runs> <nodes> <message_size> <num_fragment> <num_publishers> "
        "<min_bandwidth> <max_bandwidth> <min_latency> <max_latency> "
        "<anchor_stages> <packet_loss> <publisher_id> <publisher_rotation> "
        "<inter_message_delay> [--seed N] [--warmup-s S] ...",
    )
    for name in RUN_SH_PARAMS:
        p.add_argument(name)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--warmup-s", type=float, default=500.0)
    p.add_argument("--connect-to", type=int, default=10)  # run.sh:38
    p.add_argument("--muxer", choices=["mplex", "yamux", "quic"], default="yamux")
    p.add_argument("--no-gossip", action="store_true")
    p.add_argument("--churn", type=float, default=0.0,
                   help="per-heartbeat down-probability (failure injection)")
    p.add_argument("--use-mix", action="store_true",
                   help="route publishes through the mix network (USESMIX)")
    p.add_argument("--num-mix", type=int, default=0, help="NUMMIX")
    p.add_argument("--mix-d", type=int, default=4, help="MIXD")
    p.add_argument("--out-prefix", default="")
    p.add_argument("--stats-json", action="store_true",
                   help="also write stats<i>.json next to latencies<i>")
    p.add_argument("--checkpoint", default=None,
                   help="snapshot the experiment to this .npz during the run "
                   "(crash-resumable; see --resume; requires runs == 1)")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="messages between snapshots (raise for long "
                   "schedules at large N)")
    p.add_argument("--resume", default=None,
                   help="resume from a --checkpoint file and finish its "
                   "remaining schedule (requires runs == 1, same config)")
    p.add_argument("--gml", default=None,
                   help="ingest an existing network_topology.gml (e.g. one "
                   "the reference topogen generated) instead of rebuilding "
                   "the topology from the positional parameters")
    p.add_argument("--msgid-mode", choices=["nim", "go"], default="nim",
                   help="message-id layout: nim = random id embedded in the "
                   "payload (main.nim:169), go = timestamp-keyed "
                   "(go/rust nodes embed no id)")
    p.add_argument("--loss-mode", choices=["tcp", "message"], default="tcp",
                   help="packet-loss model for lossy topologies (-l): tcp = "
                   "RTO retransmission latency (Shadow runs real TCP "
                   "stacks), message = whole-copy drops (QUIC-unreliable "
                   "style)")
    p.add_argument("--delivery-mode", choices=["exact", "bounded"],
                   default="exact",
                   help="answered-IWANT serialization fidelity: exact = "
                   "the model of record (queued answers repaired into the "
                   "arrival times); bounded = the 100k+/1M throughput "
                   "mode (accounting/attribution exact, arrival times "
                   "keep the unserialized value where a queued answer "
                   "binds; the max queue wait is the recorded error bar)")
    a = p.parse_args(argv)
    if (a.checkpoint or a.resume) and int(a.runs) != 1:
        # per-run states would overwrite one checkpoint file and a resume
        # could not tell which run it belongs to
        p.error("--checkpoint/--resume require runs == 1")
    if a.resume and a.gml:
        # a resumed run continues on the checkpoint's embedded topology
        # matrices; silently parsing a (possibly different) GML would
        # mislead about which links are in effect
        p.error("--resume restores the checkpoint's topology; drop --gml")
    if a.use_mix:
        # a publisher that is itself a mix node is excluded from its own
        # relay path, so rotation (any ordinal publishes) or a mix-range
        # publisher_id needs one spare node
        need = a.mix_d + (
            1 if (int(a.publisher_rotation) or int(a.publisher_id) < a.num_mix)
            else 0
        )
        if a.num_mix < need:
            p.error(f"--use-mix requires --num-mix >= {need} here "
                    f"(mix-d={a.mix_d}, publisher inside mix range or "
                    f"rotation on), got {a.num_mix}")

    from .runtime.simulator import ExperimentConfig, Simulator
    from .runtime.summarize import report

    topo = _topo_from_fields(vars(a), muxer=a.muxer)
    if a.gml:
        # run an existing experiment dir: link properties come from the GML
        # (stage latencies/bandwidths), peers/messages from the positionals
        t = Topology.from_gml(a.gml, network_size=topo.network_size,
                              params=topo)
        topo = t.params
    elif a.resume:
        # the checkpoint embeds its topology; do NOT overwrite the
        # experiment dir's artifacts before (or after) validating it
        t = None
    else:
        t = Topology.build(topo)
        t.write_gml(a.out_prefix + "network_topology.gml")
        t.write_shadow_yaml(a.out_prefix + "shadow.yaml")

    large = topo.msg_size_bytes >= 1000
    for i in range(1, int(a.runs) + 1):
        print(f"Running for turn {i}")
        cfg = ExperimentConfig(
            topo=topo,
            connect_to=a.connect_to,
            # the reference nodes read GOSSIPSUB_* inside the simulation, so
            # the driver honors the same env surface (main.nim:252-306)
            gossipsub=gossipsub_params_from_env(),
            publisher_id=int(a.publisher_id),
            publisher_rotation=bool(int(a.publisher_rotation)),
            warmup_s=a.warmup_s,
            seed=a.seed + i - 1,
            with_gossip=not a.no_gossip,
            churn_down_per_hb=a.churn,
            churn_up_per_hb=a.churn / 2 if a.churn else 0.0,
            uses_mix=a.use_mix,
            num_mix=a.num_mix,
            mix_d=a.mix_d,
            msgid_mode=a.msgid_mode,
            loss_mode=a.loss_mode,
            serialize_answers=(a.delivery_mode == "exact"),
        )
        t0 = time.time()
        if a.resume:
            from .runtime.checkpoint import load_checkpoint

            sim = load_checkpoint(a.resume)
            if sim.cfg != cfg:
                p.error(
                    "--resume checkpoint was created with a different "
                    "configuration than these arguments; re-run with the "
                    "original parameters"
                )
        else:
            sim = Simulator(cfg, topology=t)
        sim.run(checkpoint_path=a.checkpoint,
                checkpoint_every=a.checkpoint_every)
        wall = time.time() - t0
        n_lines = sim.write_latencies(f"{a.out_prefix}latencies{i}")
        sim.write_shadowlog(f"{a.out_prefix}shadowlog{i}")  # run.sh:60 artifact
        s = sim.summary(large)
        print(f"Summary for turn {i}")
        print(report(s, large=large), end="")
        print(sim.bandwidth_report(), end="")  # summary_shadowlog.awk (run.sh:70-74)
        print(
            f"[tpu backend] wall={wall:.2f}s "
            f"peers*rounds/s={sim.peer_rounds_per_sec(wall):.0f} "
            f"lines={n_lines}"
        )
        if a.stats_json:
            from .runtime.summarize import sanitize_nonfinite

            with open(f"{a.out_prefix}stats{i}.json", "w") as f:
                json.dump(
                    sanitize_nonfinite({
                        "network_size": s.network_size,
                        "coverage": s.coverage(),
                        "max_latency_ms": s.max_latency_ms,
                        "avg_latency_ms": s.avg_latency_ms,
                        "avg_max_latency_ms": s.avg_max_latency_ms,
                        "wall_s": wall,
                        "peer_rounds_per_sec": sim.peer_rounds_per_sec(wall),
                    }),
                    f,
                    indent=2,
                    allow_nan=False,
                )
    return 0


def validate_attack_flags(
        scenario: str,
        *,
        mimic_margin: float | None = None,
        rotation_period_hb: int | None = None,
        dht_attack: bool = False,
        dht_heal_hb: int = -1,
        adaptive: bool = False,
        throttle_margin: float | None = None,
        px_poison_per_hb: int | None = None,
) -> None:
    """Reject incompatible `attack` scenario/flag combinations up front,
    before any topology is built or jit trace starts — a bad combo should
    cost milliseconds, not a silent no-op campaign. Raises ValueError with
    the offending flag named; cmd_attack maps it onto argparse's error path.
    """
    from .ops.adversary import ADAPTIVE_SCENARIOS

    if mimic_margin is not None and scenario != "slow_peer_mimicry":
        raise ValueError(
            f"--mimic-margin tunes the slow_peer_mimicry score setpoint; "
            f"scenario {scenario!r} never reads it — drop the flag or use "
            "--scenario slow_peer_mimicry")
    if rotation_period_hb is not None and scenario != "identity_rotation":
        raise ValueError(
            f"--rotation-period-hb sets the identity_rotation scrub cadence; "
            f"scenario {scenario!r} never reads it — drop the flag or use "
            "--scenario identity_rotation")
    if dht_attack and scenario == "cold_boot_join":
        raise ValueError(
            "--dht-eclipse/--dht-poison/--dht-cluster poison discovery "
            "state built during the attack window, but cold_boot_join "
            "replays the join race on a fresh topology with no pre-attack "
            "DHT to poison — drop the --dht-* flags or pick a scenario "
            "with an established mesh")
    if dht_heal_hb >= 0 and not dht_attack:
        raise ValueError(
            "--dht-heal-hb schedules the recovery round a DHT attack heals "
            "at, but no DHT attack is armed — add one of --dht-eclipse/"
            "--dht-poison/--dht-cluster")
    if adaptive and scenario not in ADAPTIVE_SCENARIOS:
        raise ValueError(
            f"--adaptive composes with the graft-flood family "
            f"{ADAPTIVE_SCENARIOS}, not scenario {scenario!r}: the spam "
            "scenarios have no backoff/mesh loop to adapt to, mimicry is "
            "already an adaptive policy, and rotation's identity scrubs "
            "erase the controller's own estimate")
    if throttle_margin is not None and not adaptive:
        raise ValueError("--throttle-margin tunes the adaptive duty cycle; "
                         "it needs --adaptive")
    if px_poison_per_hb is not None and not adaptive:
        raise ValueError("--px-poison-per-hb tunes the adaptive PX poison "
                         "rate; it needs --adaptive")


def cmd_attack(argv: list[str]) -> int:
    """Adversarial campaign driver: one scenario, a fraction x seed grid,
    resilience report + optional JSON/Prometheus artifacts."""
    p = argparse.ArgumentParser(prog="attack")
    from .ops.adversary import SCENARIOS

    p.add_argument("--scenario", choices=SCENARIOS,
                   default="sybil_graft_flood")
    p.add_argument("-n", "--peers", type=int, default=256)
    p.add_argument("--fractions", default="0,0.1,0.2",
                   help="comma-separated attacker fractions in [0, 1); "
                   "include 0 for the in-sweep benign baseline")
    p.add_argument("--seeds", default="0",
                   help="comma-separated trial seeds (the Monte-Carlo axis)")
    p.add_argument("--messages", type=int, default=3)
    p.add_argument("--msg-size", type=int, default=2000)
    p.add_argument("--delay-s", type=float, default=1.0,
                   help="inter-message delay in the publish schedule")
    p.add_argument("--warmup-s", type=float, default=30.0)
    p.add_argument("--attack-heartbeats", type=int, default=20,
                   help="attacked mesh-maintenance rounds before publishing")
    p.add_argument("--connect-to", type=int, default=10)
    p.add_argument("--seed", type=int, default=0,
                   help="campaign seed: builds the shared connection graph")
    p.add_argument("--publisher-id", type=int, default=4)
    p.add_argument("--violation-penalty", type=float, default=1.0)
    p.add_argument("--mimic-margin", type=float, default=None,
                   help="slow_peer_mimicry only: pin the attacker score at "
                   "this fraction of the graylist threshold (0 < m < 1)")
    p.add_argument("--rotation-period-hb", type=int, default=None,
                   help="identity_rotation only: heartbeats between "
                   "identity scrubs (>= 2)")
    # adaptive attacker controller (ops/adversary.AdaptivePolicy): the
    # per-round arms race compiled into the heartbeat scan
    p.add_argument("--adaptive", action="store_true",
                   help="arm the per-round adaptive attacker controller "
                   "(backoff-expiry regraft + PX sybil poison + recovery "
                   "slot race + score-aware duty cycle); graft-flood "
                   "scenarios only")
    p.add_argument("--throttle-margin", type=float, default=None,
                   help="adaptive duty-cycle setpoint as a fraction of the "
                   "graylist threshold (0 < m < 1); requires --adaptive")
    p.add_argument("--px-poison-per-hb", type=int, default=None,
                   help="sybil ids the adaptive attacker plants per victim "
                   "px_pool row per heartbeat; requires --adaptive")
    p.add_argument("--no-vmap", action="store_true",
                   help="run same-fraction trials sequentially instead of "
                   "one vmapped attack window")
    p.add_argument("--warm-start", action="store_true",
                   help="cross-publish warm-started fixpoints (long "
                   "schedules)")
    p.add_argument("--mesh", action="store_true",
                   help="shard the peer axis over all visible devices "
                   "(peers must divide evenly by the device count)")
    p.add_argument("--trial-groups", type=int, default=None, metavar="N",
                   help="run the campaign on the nested trial x peer grid: "
                   "N trial groups, every remaining device widening each "
                   "group's peer submesh (parallel/sharding.make_trial_mesh; "
                   "N must divide the device count). Mutually exclusive "
                   "with --mesh; 0 = one group per visible device")
    p.add_argument("--checkpoint-dir", default=None,
                   help="snapshot each trial's post-window state here")
    # mesh-repair subsystem (ops/repair.py): the recovery window + knobs
    p.add_argument("--recovery-heartbeats", type=int, default=0,
                   help="post-attack repair rounds before the publish "
                   "schedule (0 = no recovery window)")
    p.add_argument("--evict", action="store_true",
                   help="arm score-based mesh eviction in the recovery "
                   "window's heartbeats")
    p.add_argument("--eviction-threshold", type=float, default=-50.0,
                   help="PRUNE mesh members scoring below this (<= 0)")
    p.add_argument("--px", action="store_true",
                   help="peer exchange on PRUNE: pruned peers learn "
                   "score-ranked candidates and may GRAFT/dial them")
    p.add_argument("--px-count", type=int, default=6,
                   help="candidate ids carried per PRUNE")
    p.add_argument("--redial", action="store_true",
                   help="starved peers (mesh degree < D_lo for "
                   "--redial-patience heartbeats) dial new connections")
    p.add_argument("--redial-patience", type=int, default=3)
    # fault-injection subsystem (ops/faults.py): scheduled windows are in
    # heartbeat-round indices A:B relative to the attack window, half-open
    p.add_argument("--crash-frac", type=float, default=0.0,
                   help="fraction of non-publisher peers that crash for "
                   "--crash-window and restart with cold mesh/score state")
    p.add_argument("--crash-window", default="0:0", metavar="A:B",
                   help="heartbeat rounds [A, B) the crash cohort is dark")
    p.add_argument("--partition-frac", type=float, default=0.0,
                   help="fraction of peers cut onto the far side of a "
                   "two-component graph partition")
    p.add_argument("--partition-window", default="0:0", metavar="A:B",
                   help="heartbeat rounds [A, B) the partition is up")
    p.add_argument("--spike-frac", type=float, default=0.0,
                   help="fraction of peers whose uplink clocks take a "
                   "latency spike during --spike-window")
    p.add_argument("--spike-window", default="0:0", metavar="A:B",
                   help="heartbeat rounds [A, B) of the latency spike")
    p.add_argument("--spike-ms", type=float, default=0.0,
                   help="extra uplink serialization delay per spiked peer")
    # cross-protocol DHT adversary (ops/dht_adversary.py): poison the
    # discovery layer, let the repair controller's redial path draw its
    # candidates from the (possibly attacked) DHT instead of random peers
    p.add_argument("--dht-eclipse", action="store_true",
                   help="lookup eclipse: attacker responders answer "
                   "FIND_NODE with sybil-only shortlists")
    p.add_argument("--dht-poison", action="store_true",
                   help="routing-table poisoning: sybil insert waves squat "
                   "honest bucket slots")
    p.add_argument("--dht-cluster", action="store_true",
                   help="sybil key clustering: mint attacker keys inside "
                   "the victim's keyspace prefix")
    p.add_argument("--dht-heal-hb", type=int, default=-1, metavar="HB",
                   help="recovery heartbeat at which the DHT heals (the "
                   "redial pool switches to honest lookups); -1 = never")
    p.add_argument("--dht-poison-per-peer", type=int, default=8,
                   help="sybil insert attempts per honest routing table")
    p.add_argument("--dht-cluster-prefix-bits", type=int, default=16,
                   help="shared victim-prefix bits of minted sybil keys")
    p.add_argument("--dht-evict-max-fails", type=int, default=1,
                   help="failed lookups a routing-table entry survives "
                   "before eviction (retry budget)")
    p.add_argument("--dht-evict-backoff-ms", type=float, default=0.0,
                   help="exponential backoff base between retries of a "
                   "failing routing-table entry")
    # trial supervisor (SupervisorConfig): timeout + bounded retry/backoff
    p.add_argument("--trial-timeout-s", type=float, default=0.0,
                   help="wall-clock ceiling per trial batch attempt "
                   "(0 = no timeout)")
    p.add_argument("--max-retries", type=int, default=2,
                   help="retry budget per trial cell before quarantine")
    p.add_argument("--retry-backoff-s", type=float, default=0.5,
                   help="base of the exponential retry backoff")
    p.add_argument("--inject-failures", type=int, default=0,
                   help="force the first N trial attempts to fail "
                   "(supervisor smoke-test hook)")
    p.add_argument("--json", default=None,
                   help="write the campaign result as strict JSON here")
    p.add_argument("--metrics-out", default=None,
                   help="write Prometheus text exposition of the "
                   "dst_testnode_attack_* series here")
    a = p.parse_args(argv)

    def _window(spec: str, flag: str) -> tuple[int, int]:
        try:
            lo, hi = spec.split(":")
            return int(lo), int(hi)
        except ValueError:
            p.error(f"{flag} must be A:B heartbeat indices, got {spec!r}")

    from .ops.adversary import AdaptivePolicy, AdversaryParams
    from .ops.dht_adversary import DhtAdversaryParams
    from .ops.faults import FaultParams
    from .ops.repair import RepairParams
    from .runtime.campaign import (
        CampaignConfig, SupervisorConfig, attack_gossipsub, run_campaign)
    from .runtime.simulator import ExperimentConfig
    from .runtime.summarize import report_campaign

    try:
        validate_attack_flags(
            a.scenario,
            mimic_margin=a.mimic_margin,
            rotation_period_hb=a.rotation_period_hb,
            dht_attack=(a.dht_eclipse or a.dht_poison or a.dht_cluster),
            dht_heal_hb=a.dht_heal_hb,
            adaptive=a.adaptive,
            throttle_margin=a.throttle_margin,
            px_poison_per_hb=a.px_poison_per_hb,
        )
    except ValueError as e:
        p.error(str(e))

    fractions = tuple(float(s) for s in a.fractions.split(",") if s.strip())
    seeds = tuple(int(s) for s in a.seeds.split(",") if s.strip())
    adv_kw: dict = {}
    if a.mimic_margin is not None:
        adv_kw["mimic_margin"] = a.mimic_margin
    if a.rotation_period_hb is not None:
        adv_kw["rotation_period_hb"] = a.rotation_period_hb
    if a.adaptive:
        pol_kw: dict = {"enabled": True}
        if a.throttle_margin is not None:
            pol_kw["throttle_margin"] = a.throttle_margin
        if a.px_poison_per_hb is not None:
            pol_kw["px_poison_per_hb"] = a.px_poison_per_hb
        adv_kw["adaptive"] = AdaptivePolicy(**pol_kw)
    # eclipse needs a mesh-bound publish to have anything to eclipse
    gs = attack_gossipsub(
        flood_publish=(a.scenario != "eclipse_publisher"))
    cfg = CampaignConfig(
        scenario=a.scenario,
        fractions=fractions,
        seeds=seeds,
        experiment=ExperimentConfig(
            topo=TopoParams(
                network_size=a.peers, anchor_stages=3,
                msg_size_bytes=a.msg_size, messages=a.messages,
                delay_seconds=a.delay_s),
            connect_to=a.connect_to,
            gossipsub=gs,
            publisher_id=a.publisher_id,
            warmup_s=a.warmup_s,
            seed=a.seed,
            warm_start=a.warm_start,
        ),
        adversary=AdversaryParams(
            scenario=a.scenario, violation_penalty=a.violation_penalty,
            **adv_kw),
        attack_heartbeats=a.attack_heartbeats,
        vmap_trials=not a.no_vmap,
        checkpoint_dir=a.checkpoint_dir,
        recovery_heartbeats=a.recovery_heartbeats,
        repair=RepairParams(
            evict=a.evict, eviction_threshold=a.eviction_threshold,
            px=a.px, px_count=a.px_count,
            redial=a.redial, redial_patience=a.redial_patience),
        faults=FaultParams(
            crash_frac=a.crash_frac,
            crash_window=_window(a.crash_window, "--crash-window"),
            partition_frac=a.partition_frac,
            partition_window=_window(a.partition_window,
                                     "--partition-window"),
            spike_frac=a.spike_frac,
            spike_window=_window(a.spike_window, "--spike-window"),
            spike_ms=a.spike_ms),
        dht=DhtAdversaryParams(
            lookup_eclipse=a.dht_eclipse,
            rtable_poison=a.dht_poison,
            sybil_cluster=a.dht_cluster,
            heal_hb=a.dht_heal_hb,
            poison_per_peer=a.dht_poison_per_peer,
            cluster_prefix_bits=a.dht_cluster_prefix_bits,
            evict_max_fails=a.dht_evict_max_fails,
            evict_backoff_ms=a.dht_evict_backoff_ms),
        supervisor=SupervisorConfig(
            trial_timeout_s=a.trial_timeout_s,
            max_retries=a.max_retries,
            retry_backoff_s=a.retry_backoff_s,
            inject_failures=a.inject_failures),
    )
    mesh = None
    if a.mesh:
        from .parallel.sharding import make_peer_mesh

        mesh = make_peer_mesh()
        if a.peers % len(mesh.devices.flat) != 0:
            p.error(f"--mesh needs peers ({a.peers}) divisible by the "
                    f"device count ({len(mesh.devices.flat)})")
    trial_mesh = None
    if a.trial_groups is not None:
        if a.mesh:
            p.error("--trial-groups and --mesh are mutually exclusive "
                    "(the trial grid already owns every device)")
        from .parallel.sharding import make_trial_mesh

        try:
            trial_mesh = make_trial_mesh(a.trial_groups or None)
        except ValueError as e:
            p.error(str(e))
    t0 = time.time()
    res = run_campaign(cfg, mesh=mesh, trial_mesh=trial_mesh)
    wall = time.time() - t0
    d = res.to_dict()
    print(report_campaign(d), end="")
    if a.json:
        with open(a.json, "w") as f:
            # strict JSON: non-finite metrics are already nulled by to_dict
            json.dump(d, f, indent=2, allow_nan=False)
    if a.metrics_out:
        from .runtime.metrics import CampaignMetrics

        m = CampaignMetrics()
        m.fill_from_campaign(d)
        with open(a.metrics_out, "w") as f:
            f.write(m.render())
    print(f"[tpu backend] wall={wall:.2f}s trials={len(res.trials)} "
          f"trials/s={res.trials_per_s:.3f}")
    return 0


def cmd_pareto(argv: list[str]) -> int:
    """Defense Pareto sweep: grid the score-defense knobs (mesh degree band,
    slow-peer penalty weight) against the ADAPTIVE attacker and report the
    coverage-vs-bandwidth-vs-recovery-time front (runtime/campaign.
    run_defense_sweep). Every grid point is a full campaign under a fresh
    GossipSubParams — i.e. a fresh jit cache entry — so keep grids small."""
    p = argparse.ArgumentParser(prog="pareto")
    from .ops.adversary import ADAPTIVE_SCENARIOS

    p.add_argument("--scenario", choices=ADAPTIVE_SCENARIOS,
                   default="eclipse_publisher",
                   help="adaptive-capable scenario the sweep defends "
                   "against (eclipse_publisher gives the sharpest "
                   "recovery_time_ms separation)")
    p.add_argument("-n", "--peers", type=int, default=64)
    p.add_argument("--fractions", default="0.2",
                   help="comma-separated ATTACKED fractions (> 0); the "
                   "sweep aggregates over all of them")
    p.add_argument("--seeds", default="0,1")
    p.add_argument("--messages", type=int, default=2)
    p.add_argument("--msg-size", type=int, default=2000)
    p.add_argument("--delay-s", type=float, default=0.5)
    p.add_argument("--warmup-s", type=float, default=8.0)
    p.add_argument("--attack-heartbeats", type=int, default=6)
    p.add_argument("--recovery-heartbeats", type=int, default=8)
    p.add_argument("--connect-to", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--publisher-id", type=int, default=4)
    p.add_argument("--throttle-margin", type=float, default=None,
                   help="adaptive duty-cycle setpoint (0 < m < 1)")
    p.add_argument("--degree-grid", default="4:6:8,4:4:6",
                   metavar="DL:D:DH[,...]",
                   help="comma-separated d_low:d:d_high degree bands to "
                   "sweep (the defaults are inserted if absent)")
    p.add_argument("--weight-grid", default="-10",
                   metavar="W[,...]",
                   help="comma-separated slow_peer_penalty_weight values "
                   "(<= 0) to sweep")
    p.add_argument("--trial-groups", type=int, default=None, metavar="N",
                   help="nested trial x peer sharding for every campaign "
                   "in the sweep (parallel/sharding.make_trial_mesh)")
    p.add_argument("--json", default=None,
                   help="write the sweep artifact as strict JSON here")
    a = p.parse_args(argv)

    from .ops.adversary import AdaptivePolicy, AdversaryParams
    from .ops.repair import RepairParams
    from .runtime.campaign import (
        CampaignConfig, attack_gossipsub, run_defense_sweep)
    from .runtime.simulator import ExperimentConfig
    from .runtime.summarize import report_defense_sweep

    try:
        degree_grid = tuple(
            tuple(int(x) for x in band.split(":"))
            for band in a.degree_grid.split(",") if band.strip())
        if any(len(b) != 3 for b in degree_grid):
            raise ValueError
    except ValueError:
        p.error(f"--degree-grid must be DL:D:DH[,DL:D:DH...], got "
                f"{a.degree_grid!r}")
    weight_grid = tuple(
        float(s) for s in a.weight_grid.split(",") if s.strip())
    fractions = tuple(float(s) for s in a.fractions.split(",") if s.strip())
    if not fractions or any(f <= 0.0 for f in fractions):
        p.error("--fractions must list attacked fractions > 0 (the sweep "
                "measures the defense against the armed attacker; benign "
                "baselines belong to the attack subcommand)")
    seeds = tuple(int(s) for s in a.seeds.split(",") if s.strip())
    pol_kw: dict = {"enabled": True}
    if a.throttle_margin is not None:
        pol_kw["throttle_margin"] = a.throttle_margin
    cfg = CampaignConfig(
        scenario=a.scenario,
        fractions=fractions,
        seeds=seeds,
        experiment=ExperimentConfig(
            topo=TopoParams(
                network_size=a.peers, anchor_stages=3,
                msg_size_bytes=a.msg_size, messages=a.messages,
                delay_seconds=a.delay_s),
            connect_to=a.connect_to,
            gossipsub=attack_gossipsub(
                flood_publish=(a.scenario != "eclipse_publisher")),
            publisher_id=a.publisher_id,
            warmup_s=a.warmup_s,
            seed=a.seed,
        ),
        adversary=AdversaryParams(
            scenario=a.scenario, adaptive=AdaptivePolicy(**pol_kw)),
        attack_heartbeats=a.attack_heartbeats,
        recovery_heartbeats=a.recovery_heartbeats,
        repair=RepairParams(evict=True, px=True, redial=True),
    )
    trial_mesh = None
    if a.trial_groups is not None:
        from .parallel.sharding import make_trial_mesh

        try:
            trial_mesh = make_trial_mesh(a.trial_groups or None)
        except ValueError as e:
            p.error(str(e))
    sweep = run_defense_sweep(cfg, degree_grid=degree_grid,
                              weight_grid=weight_grid,
                              trial_mesh=trial_mesh)
    print(report_defense_sweep(sweep), end="")
    if a.json:
        with open(a.json, "w") as f:
            # strict JSON: run_defense_sweep sanitizes non-finite values
            json.dump(sweep, f, indent=2, allow_nan=False)
    return 0


def cmd_arena(argv: list[str]) -> int:
    """Protocol arena: race GossipSub against the episub tree backend on
    identical epoch graphs, traffic schedules, fault cohorts, and the
    adaptive attacker (runtime/campaign.run_arena_campaign), and report
    the per-scenario win matrix. The benign scenario rides along by
    default — it is the bandwidth-floor row the arena bench gate reads."""
    p = argparse.ArgumentParser(prog="arena")
    from .ops.adversary import ADAPTIVE_SCENARIOS

    p.add_argument("--scenarios", default="benign,sybil_graft_flood",
                   help="comma-separated scenario list; 'benign' is the "
                   "reserved no-attacker row, the rest must be "
                   f"adaptive-capable ({', '.join(ADAPTIVE_SCENARIOS)})")
    p.add_argument("-n", "--peers", type=int, default=64)
    p.add_argument("--fraction", type=float, default=0.25,
                   help="attacker fraction for every attack scenario")
    p.add_argument("--seeds", default="0,1")
    p.add_argument("--messages", type=int, default=2)
    p.add_argument("--msg-size", type=int, default=2000)
    p.add_argument("--delay-s", type=float, default=0.5)
    p.add_argument("--warmup-s", type=float, default=8.0)
    p.add_argument("--attack-heartbeats", type=int, default=8)
    p.add_argument("--connect-to", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--publisher-id", type=int, default=4)
    p.add_argument("--throttle-margin", type=float, default=None,
                   help="adaptive duty-cycle setpoint (0 < m < 1)")
    p.add_argument("--lazy-degree", type=int, default=None,
                   help="episub lazy-IHAVE budget per round (default: "
                   "the GossipSub d_lazy derivation)")
    p.add_argument("--trial-groups", type=int, default=None, metavar="N",
                   help="nested trial x peer sharding for both windows "
                   "(parallel/sharding.make_trial_mesh)")
    p.add_argument("--json", default=None,
                   help="write the arena artifact as strict JSON here")
    a = p.parse_args(argv)

    from .ops.adversary import AdaptivePolicy, AdversaryParams
    from .ops.episub import EpisubParams
    from .runtime.campaign import (
        CampaignConfig, attack_gossipsub, run_arena_campaign)
    from .runtime.simulator import ExperimentConfig
    from .runtime.summarize import report_arena

    scenarios = tuple(s.strip() for s in a.scenarios.split(",") if s.strip())
    attack_scs = [s for s in scenarios if s != "benign"]
    bad = [s for s in attack_scs if s not in ADAPTIVE_SCENARIOS]
    if bad:
        p.error(f"scenarios {bad} are not adaptive-capable; choose from "
                f"'benign', {', '.join(ADAPTIVE_SCENARIOS)}")
    if not attack_scs:
        p.error("--scenarios needs at least one attack scenario beside "
                "'benign' (the arena's referee is the adaptive attacker)")
    if not 0.0 < a.fraction < 1.0:
        p.error("--fraction must be in (0, 1)")
    seeds = tuple(int(s) for s in a.seeds.split(",") if s.strip())
    pol_kw: dict = {"enabled": True}
    if a.throttle_margin is not None:
        pol_kw["throttle_margin"] = a.throttle_margin
    cfg = CampaignConfig(
        scenario=attack_scs[0],
        fractions=(a.fraction,),
        seeds=seeds,
        experiment=ExperimentConfig(
            topo=TopoParams(
                network_size=a.peers, anchor_stages=3,
                msg_size_bytes=a.msg_size, messages=a.messages,
                delay_seconds=a.delay_s),
            connect_to=a.connect_to,
            # flood_publish off: arena traffic must ride mesh_mask, the
            # surface the two protocols differ on
            gossipsub=attack_gossipsub(flood_publish=False),
            publisher_id=a.publisher_id,
            warmup_s=a.warmup_s,
            seed=a.seed,
        ),
        adversary=AdversaryParams(
            scenario=attack_scs[0], adaptive=AdaptivePolicy(**pol_kw)),
        attack_heartbeats=a.attack_heartbeats,
    )
    ep = None
    if a.lazy_degree is not None:
        ep = EpisubParams(root=a.publisher_id % a.peers,
                          lazy_degree=a.lazy_degree)
    trial_mesh = None
    if a.trial_groups is not None:
        from .parallel.sharding import make_trial_mesh

        try:
            trial_mesh = make_trial_mesh(a.trial_groups or None)
        except ValueError as e:
            p.error(str(e))
    arena = run_arena_campaign(cfg, scenarios=scenarios, ep=ep,
                               trial_mesh=trial_mesh)
    print(report_arena(arena), end="")
    if a.json:
        with open(a.json, "w") as f:
            # strict JSON: run_arena_campaign sanitizes non-finite values
            json.dump(arena, f, indent=2, allow_nan=False)
    return 0


def cmd_serve(argv: list[str]) -> int:
    """Run as a long-lived node service (the reference's steady-state node:
    HTTP /publish + /health + /ready on :8645, Prometheus on :8008), hosting
    the whole simulated network in-process and exposing the env-selected
    peer's view (getPeerDetails, env.nim:13-36)."""
    p = argparse.ArgumentParser(prog="serve")
    p.add_argument("--control-port", type=int, default=None)
    p.add_argument("--metrics-port", type=int, default=None)
    p.add_argument("--time-scale", type=float, default=1.0,
                   help="simulated seconds advanced per wall second")
    p.add_argument("--tick-s", type=float, default=1.0)
    p.add_argument("--duration-s", type=float, default=None)
    p.add_argument("--warmup-s", type=float, default=15.0,
                   help="heartbeats run before serving (mesh stabilization, "
                   "main.nim:466-477)")
    p.add_argument("--store-metrics-dir", default=None)
    # resident-runtime surface (ARCHITECTURE §16): admission control,
    # batching dispatch, supervision, crash-safe warm restart
    p.add_argument("--queue-depth", type=int, default=1024,
                   help="bounded admission queue; overflow answers 429")
    p.add_argument("--device-ms-budget", type=float, default=0.0,
                   help="reject once est. queued device ms exceeds this")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="default per-request sim-time deadline (0 = none)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="requests per service round (tenant round-robin)")
    p.add_argument("--dispatch-mode", default="batched",
                   choices=("batched", "sequential"),
                   help="batched = one stacked device dispatch per "
                   "same-shape group of the round (ISSUE 14); sequential = "
                   "the pinned per-request reference path")
    p.add_argument("--dispatch-timeout-s", type=float, default=0.0)
    p.add_argument("--max-retries", type=int, default=1)
    p.add_argument("--retry-backoff-s", type=float, default=0.05)
    p.add_argument("--inject-failures", type=int, default=0,
                   help="force the first K dispatch attempts to fail (CI)")
    p.add_argument("--checkpoint", default=None,
                   help="service checkpoint path (periodic + final flush)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="flush every K service rounds (0 = final only)")
    p.add_argument("--drain-deadline-s", type=float, default=5.0)
    p.add_argument("--resume", action="store_true",
                   help="warm-restart from --checkpoint if it exists")
    a = p.parse_args(argv)

    from .config.env import (
        HTTP_CONTROL_PORT,
        PROMETHEUS_PORT,
        env_float,
        get_peer_details,
    )
    from .runtime.node_service import ServiceConfig, serve_forever
    from .runtime.simulator import ExperimentConfig, Simulator

    node = get_peer_details()
    node.validate()  # reject unknown muxer / connect_to >= peers at startup
    svc_cfg = ServiceConfig(
        max_queue_depth=a.queue_depth,
        device_ms_budget=a.device_ms_budget,
        default_deadline_ms=a.deadline_ms,
        max_batch=a.max_batch,
        dispatch_mode=a.dispatch_mode,
        dispatch_timeout_s=a.dispatch_timeout_s,
        max_retries=a.max_retries,
        retry_backoff_s=a.retry_backoff_s,
        inject_failures=a.inject_failures,
        checkpoint_path=a.checkpoint,
        checkpoint_every=a.checkpoint_every,
        drain_deadline_s=a.drain_deadline_s,
    )
    svc_cfg.validate()
    if a.resume and not a.checkpoint:
        p.error("--resume requires --checkpoint")
    resume_from = a.checkpoint if (a.resume and a.checkpoint
                                   and os.path.exists(a.checkpoint)) else None
    if resume_from is not None:
        # warm restart: the checkpoint carries sim + service state, so skip
        # building and warming a simulator that restore() would discard
        store_dir = a.store_metrics_dir
        if store_dir is None and node.in_shadow:
            store_dir = "."
        control = (a.control_port if a.control_port is not None
                   else HTTP_CONTROL_PORT)
        metrics = (a.metrics_port if a.metrics_port is not None
                   else PROMETHEUS_PORT)
        print(f"node service warm-restarting from {resume_from}, "
              f"control :{control} metrics :{metrics}")
        serve_forever(
            None, node,
            control_port=control, metrics_port=metrics,
            time_scale=a.time_scale, tick_s=a.tick_s,
            duration_s=a.duration_s,
            store_metrics_dir=store_dir, out=sys.stdout,
            service=svc_cfg, resume_from=resume_from,
        )
        return 0
    topo = TopoParams(
        network_size=node.network_size,
        muxer=node.muxer,
        num_frags=node.fragments,
    )
    topics = tuple(
        s.strip() for s in env_str("TOPICS", "").split(",") if s.strip())
    if len(topics) == 1:
        node.topic = topics[0]  # single custom topic, single-topic engine
    if len(topics) > 1:
        # multi-topic node: /publish routes by topic name (BASELINE config 3
        # surface); SUBSCRIBE_FRACTION < 1 subscribes each peer per topic
        if node.uses_mix or node.mounts_mix:
            p.error("mix routing (USESMIX/MOUNTSMIX) is single-topic only; "
                    "drop TOPICS or the mix surface")
        from .runtime.multitopic import MultiTopicConfig, MultiTopicSimulator

        sim = MultiTopicSimulator(MultiTopicConfig(
            topo=topo,
            topics=topics,
            connect_to=node.connect_to,
            gossipsub=node.gossipsub,
            warmup_s=a.warmup_s,
            subscribe_fraction=env_float("SUBSCRIBE_FRACTION", 1.0),
            max_connections=node.max_connections,
            self_trigger=node.self_trigger,
        ))
    else:
        cfg = ExperimentConfig(
            topo=topo,
            connect_to=node.connect_to,
            gossipsub=node.gossipsub,
            warmup_s=a.warmup_s,
            self_trigger=node.self_trigger,
            max_connections=node.max_connections,
            uses_mix=node.uses_mix,
            num_mix=node.num_mix,
            mix_d=node.mix_d,
        )
        sim = Simulator(cfg)
    sim.warmup()
    store_dir = a.store_metrics_dir
    if store_dir is None and node.in_shadow:
        store_dir = "."  # in-Shadow persistence default (env.nim:58-73)
    control = a.control_port if a.control_port is not None else HTTP_CONTROL_PORT
    metrics = a.metrics_port if a.metrics_port is not None else PROMETHEUS_PORT
    print(
        f"node service up: {node.network_size} peers simulated, node view "
        f"peer {node.my_id}, control :{control} metrics :{metrics}"
    )
    serve_forever(
        sim, node,
        control_port=control, metrics_port=metrics,
        time_scale=a.time_scale, tick_s=a.tick_s, duration_s=a.duration_s,
        store_metrics_dir=store_dir, out=sys.stdout,
        service=svc_cfg,
    )
    return 0


def cmd_kad(argv: list[str]) -> int:
    """Role-based kad-dht workload (kad-dht/main.nim:15-72): bootstrap
    anchors + RoleNormal warmup + RoleProbe lookup loop, batched."""
    p = argparse.ArgumentParser(prog="kad")
    p.add_argument("-n", "--nodes", type=int, default=None,
                   help="defaults to PEERS env")
    p.add_argument("--bootstraps", type=int, default=None)
    p.add_argument("--probes", type=int, default=None)
    p.add_argument("--discovery", choices=["kad-dht", "extended"], default=None)
    p.add_argument("--duration-s", type=float, default=60.0)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--log", default=None, help="write node log lines here")
    a = p.parse_args(argv)

    from .runtime.kad_runtime import KadSimulator, config_from_env

    cfg = config_from_env()
    if a.nodes is not None:
        cfg.network_size = a.nodes
    if a.bootstraps is not None:
        cfg.n_bootstrap = a.bootstraps
    if a.probes is not None:
        cfg.n_probe = a.probes
    if a.discovery is not None:
        cfg.discovery = a.discovery
    if a.seed is not None:
        cfg.seed = a.seed
    cfg.probe_duration_s = a.duration_s
    cfg.validate()
    t0 = time.time()
    sim = KadSimulator(cfg)
    summary = sim.run()
    wall = time.time() - t0
    if a.log:
        with open(a.log, "w") as f:
            f.write("\n".join(sim.lines) + "\n")
    print(summary.report())
    print(f"[tpu backend] wall={wall:.2f}s lookups={len(sim.lookups)}")
    return 0


def cmd_connmanager(argv: list[str]) -> int:
    """Hub-and-spoke connection-manager stress (connmanager/main.nim):
    watermark trimming + reconnect strategies, driven by the WATERMARK_*/
    RECONNECT env surface with flag overrides."""
    p = argparse.ArgumentParser(prog="connmanager")
    p.add_argument("--duration-s", type=int, default=None)
    p.add_argument("--trace", default=None,
                   help="write the per-tick hub connection counts (CSV)")
    a = p.parse_args(argv)

    from .ops.connmanager import config_from_env, run_connmanager

    cfg = config_from_env()
    if a.duration_s is not None:
        cfg.duration_s = a.duration_s
    t0 = time.time()
    summary, _ = run_connmanager(cfg)
    wall = time.time() - t0
    if a.trace:
        import numpy as np

        np.savetxt(a.trace, summary.trace, fmt="%d", delimiter=",")
    print(summary.report())
    print(f"[tpu backend] wall={wall:.2f}s ticks={len(summary.trace)}")
    return 0


def cmd_regression(argv: list[str]) -> int:
    """Regression workload (regression/main.nim): GossipSub mesh formed via
    kad-dht bootstrap + mesh ping probes + standard latency output."""
    p = argparse.ArgumentParser(prog="regression")
    p.add_argument("-n", "--nodes", type=int, default=None)
    p.add_argument("--messages", type=int, default=None)
    p.add_argument("--msg-size", type=int, default=None)
    p.add_argument("--log", default=None)
    p.add_argument("--latencies", default=None,
                   help="write awk-compatible latencies file here")
    a = p.parse_args(argv)

    from .runtime.logemit import LatenciesWriter
    from .runtime.regression_runtime import (
        RegressionSimulator,
        config_from_env as regression_config,
    )

    cfg = regression_config()
    if a.nodes is not None:
        cfg.network_size = a.nodes
    if a.messages is not None:
        cfg.messages = a.messages
    if a.msg_size is not None:
        cfg.msg_size = a.msg_size
    cfg.validate()
    t0 = time.time()
    sim = RegressionSimulator(cfg)
    summary = sim.run()
    wall = time.time() - t0
    if a.log:
        with open(a.log, "w") as f:
            f.write("\n".join(sim.lines) + "\n")
    if a.latencies:
        w = LatenciesWriter()
        for rec in sim.records():
            w.add_message(rec.msg_id, rec.receivers, rec.delays_ms_int)
        w.write(a.latencies)
    print(summary.report())
    print(f"[tpu backend] wall={wall:.2f}s")
    return 0


def cmd_servicedisco(argv: list[str]) -> int:
    """Service-discovery workload (service-discovery/main.nim): advertisers
    + discoverers + hybrid over the DHT, env-driven with flag overrides."""
    p = argparse.ArgumentParser(prog="servicedisco")
    p.add_argument("-n", "--nodes", type=int, default=None)
    p.add_argument("--duration-s", type=int, default=None)
    p.add_argument("--services", default=None,
                   help="comma-separated (ADVERTISE_SERVICES)")
    p.add_argument("--log", default=None)
    a = p.parse_args(argv)

    from .runtime.sd_runtime import SDSimulator, config_from_env

    cfg = config_from_env()
    if a.nodes is not None:
        cfg.network_size = a.nodes
    if a.duration_s is not None:
        cfg.duration_s = a.duration_s
    if a.services:
        cfg.services = [s.strip() for s in a.services.split(",") if s.strip()]
    cfg.validate()
    t0 = time.time()
    sim = SDSimulator(cfg)
    summary = sim.run()
    wall = time.time() - t0
    if a.log:
        with open(a.log, "w") as f:
            f.write("\n".join(sim.lines) + "\n")
    print(summary.report())
    print(f"[tpu backend] wall={wall:.2f}s")
    return 0


def cmd_inject(argv: list[str]) -> int:
    """Publisher controller against running `serve` nodes — the traffic_sync
    surface (-s size, -m messages, -d delay, --peer-selection id|rotation)."""
    p = argparse.ArgumentParser(prog="inject")
    p.add_argument("targets", nargs="+",
                   help="node control endpoints (host[:port] or URL)")
    p.add_argument("-s", "--msg-size", type=int, default=1500)
    p.add_argument("-m", "--messages", type=int, default=10)
    p.add_argument("-d", "--delay-s", type=float, default=1.0)
    p.add_argument("--topic", default="test")
    p.add_argument("--peer-selection", choices=["id", "rotation"], default="id")
    p.add_argument("--publisher-id", type=int, default=0)
    p.add_argument("--burst", type=int, default=1,
                   help="messages posted back-to-back before each delay — "
                   "gives a batched-dispatch service multi-request rounds")
    a = p.parse_args(argv)

    from .runtime.publisher import inject

    res = inject(
        a.targets, a.msg_size, a.messages, a.delay_s, topic=a.topic,
        peer_selection=a.peer_selection, publisher_id=a.publisher_id,
        burst=a.burst,
    )
    for r in res.replies:
        print(json.dumps(r, allow_nan=False))
    print(f"published ok={res.ok} failed={res.failed}")
    return 0 if res.failed == 0 else 1


def cmd_lint(argv: list[str]) -> int:
    """graft-audit: static certification of the hot paths (analysis/).

    Runs the AST lint over the package + bench/scripts sources and the
    jaxpr auditor over every registered entrypoint contract, then emits a
    strict-JSON violation report on stdout. Exit 0 iff clean.
    """
    p = argparse.ArgumentParser(prog="lint")
    p.add_argument("paths", nargs="*",
                   help="files/dirs for the AST engine (default: the repo's "
                        "python surface: package, bench*.py, scripts/)")
    p.add_argument("--no-ast", action="store_true",
                   help="skip the AST lint engine")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr auditor (fast, no jax tracing)")
    p.add_argument("--checkify", action="store_true",
                   help="also run the opt-in runtime half of the contracts "
                        "(executes small configs under jax.experimental."
                        "checkify; slower)")
    p.add_argument("--sharding", action="store_true",
                   help="also run the sharding auditor (GA-S rules): "
                        "compile every registered contract and walk the "
                        "GSPMD output for collectives / replication / "
                        "per-device memory (slower — real XLA compiles)")
    p.add_argument("--only", default=None, metavar="PREFIX",
                   help="restrict the jaxpr + sharding engines to "
                        "contracts whose name starts with PREFIX (e.g. "
                        "campaign/)")
    p.add_argument("--predict-rung", nargs="?", const=1048576, type=int,
                   default=None, metavar="PEERS",
                   help="also fit the attack-window footprint curves and "
                        "emit the rung feasibility certificate for PEERS "
                        "(default 1048576) on a modeled v5e-8")
    p.add_argument("--rung-dcn", type=int, default=1, metavar="HOSTS",
                   help="model the rung on a HOSTS-strong pod of v5e-8 "
                        "slices joined over DCN (make_dcn_mesh placement: "
                        "each host holds its own stacked-trial slice; "
                        "default 1 = the single-slice rung)")
    p.add_argument("--rung-scenario", choices=("attack", "arena"),
                   default="attack",
                   help="which window family to fit: the GossipSub attack "
                        "window (default) or the protocol-arena window "
                        "with its EpisubCtrl leaves")
    p.add_argument("--rung-out", default=None, metavar="PATH",
                   help="also write the rung certificate alone to PATH "
                        "(strict JSON; the report embeds it either way)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the strict-JSON report to PATH instead of "
                        "stdout (github annotations still print to stdout)")
    p.add_argument("--format", choices=("json", "github"), default="json",
                   help="'github' additionally emits ::error/::notice "
                        "workflow-command lines so GA-* findings render "
                        "inline on PRs")
    a = p.parse_args(argv)

    from .analysis import audit_contracts, lint_paths, render_report, run_checkify
    from .analysis.registry import default_contracts
    from .analysis.report import github_annotations

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = []
    waived: list[dict] = []
    sharding_facts = None
    rung_cert = None
    checked_files = 0
    checked_entrypoints = 0

    if not a.no_ast:
        if a.paths:
            targets = a.paths
        else:
            pkg = os.path.dirname(os.path.abspath(__file__))
            targets = [pkg]
            for extra in ("bench.py", "bench_configs.py", "scripts"):
                cand = os.path.join(repo_root, extra)
                if os.path.exists(cand):
                    targets.append(cand)
        ast_violations, checked_files = lint_paths(targets, repo_root)
        violations.extend(ast_violations)

    contracts = default_contracts()
    if a.only:
        contracts = [c for c in contracts if c.name.startswith(a.only)]
    if not a.no_jaxpr:
        checked_entrypoints = len(contracts)
        violations.extend(audit_contracts(contracts))
        if a.checkify:
            violations.extend(run_checkify(contracts))

    if a.sharding:
        from .analysis.sharding_audit import audit_sharding_contracts

        checked_entrypoints = max(checked_entrypoints, len(contracts))
        sh_violations, waived, sharding_facts = audit_sharding_contracts(
            contracts)
        violations.extend(sh_violations)

    if a.predict_rung is not None:
        from .analysis.sharding_audit import predict_rung_certificate

        spec_builder = None
        scenario = "sybil_graft_flood"
        if a.rung_scenario == "arena":
            from .analysis.registry import arena_rung_spec

            def spec_builder(n):
                return arena_rung_spec(n)

            scenario = "protocol_arena/episub"
        rung_cert = predict_rung_certificate(
            rung_peers=a.predict_rung, dcn=a.rung_dcn,
            spec_builder=spec_builder, scenario=scenario)
        if a.rung_out:
            with open(a.rung_out, "w") as fh:
                json.dump(rung_cert, fh, indent=2, sort_keys=True,
                          allow_nan=False)
                fh.write("\n")

    if a.format == "github":
        for line in github_annotations(violations, waived):
            print(line)
    report = render_report(
        violations, checked_files=checked_files,
        checked_entrypoints=checked_entrypoints,
        sharding=sharding_facts, waived=waived if a.sharding else None,
        rung=rung_cert)
    if a.out:
        with open(a.out, "w") as fh:
            fh.write(report + "\n")
    else:
        print(report)
    return 1 if violations else 0


def cmd_conform(argv: list[str]) -> int:
    """Conformance oracle: spec-differential certification of the compiled
    step against the pure-numpy GossipSub v1.1 reference model.

    Emits the strict-JSON certificate (stdout or --out). Exit 0 iff every
    divergence is absent or carries a documented_choice waiver
    (docs/CONFORMANCE.md); any sim_bug is a hard failure.
    """
    p = argparse.ArgumentParser(prog="conform")
    p.add_argument("--all-scenarios", action="store_true",
                   help="run the full canon: all 8 attack scenarios plus "
                        "the adaptive, faults, churn and cross-fragment "
                        "entries (default when no --scenario is given)")
    p.add_argument("--scenario", action="append", default=None,
                   help="restrict to specific attack scenario(s); "
                        "repeatable. Skips the adaptive/faults/churn/"
                        "gossip entries unless --all-scenarios is also set")
    p.add_argument("--n", type=int, default=48,
                   help="peers per differential instance (default 48)")
    p.add_argument("--connect-to", type=int, default=8)
    p.add_argument("--steps", type=int, default=8,
                   help="attack heartbeats walked per instance")
    p.add_argument("--warm-steps", type=int, default=4)
    p.add_argument("--seeds", type=int, nargs="+", default=[0],
                   help="fuzz seeds; each reseeds graph, state and cohort")
    p.add_argument("--fuzz", type=int, default=0, metavar="N",
                   help="append N random-parameter-grid entries: each "
                        "samples degree bounds (0 < d_low <= d <= d_high "
                        "<= capacity), gossip factor and score weights, "
                        "then runs the differential under that grid, "
                        "cycling through the attack canon. One jit compile "
                        "per sample")
    p.add_argument("--fuzz-seed", type=int, default=0,
                   help="PRNG stream for --fuzz grid sampling (default 0)")
    p.add_argument("--out", default=None,
                   help="certificate path (default: stdout)")
    a = p.parse_args(argv)

    from .analysis.conformance import (conformance_certificate,
                                       write_certificate)
    from .runtime.summarize import sanitize_nonfinite

    full = a.all_scenarios or a.scenario is None
    cert = conformance_certificate(
        scenarios=a.scenario, n=a.n, connect_to=a.connect_to,
        seeds=tuple(a.seeds), steps=a.steps, warm_steps=a.warm_steps,
        include_adaptive=full, include_faults=full, include_churn=full,
        include_gossip=full, fuzz=a.fuzz, fuzz_seed=a.fuzz_seed)
    if a.out:
        write_certificate(cert, a.out)
    else:
        print(json.dumps(sanitize_nonfinite(cert), indent=2,
                         allow_nan=False))
    for e in cert["entries"]:
        line = f"conform: {e['scenario']:<22} {e['status']}"
        if e["divergences"]:
            line += f" ({len(e['divergences'])} divergence(s), " \
                    f"{e['sim_bugs']} sim_bug(s))"
        print(line, file=sys.stderr)
    return 0 if cert["clean"] else 1


def cmd_microbench(argv: list[str]) -> int:
    """Microbenchmark + autotune harness (runtime/microbench.py): roofline
    coordinates per registered entrypoint, the Pallas row-block sweep, and
    the packed_state A/B. Strict-JSON artifact, exit 0 on success."""
    from .runtime.microbench import run

    run(argv)
    return 0


def cmd_trace(argv: list[str]) -> int:
    """Flight-recorder trace export: a self-contained mini-run (warmup
    untraced, then a recorded window) whose per-heartbeat tel_* curves are
    written as a perfetto-loadable Chrome-trace JSON plus .npz/CSV sidecars.
    Strict-JSON summary on stdout, exit 0 on success."""
    p = argparse.ArgumentParser(prog="trace")
    p.add_argument("-n", "--network-size", type=int, default=64)
    p.add_argument("--connect-to", type=int, default=6)
    p.add_argument("--heartbeats", type=int, default=20,
                   help="recorded window length in heartbeats")
    p.add_argument("--warmup-hb", type=int, default=10,
                   help="untraced mesh-stabilization rounds before recording")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--degree-bins", type=int, default=12)
    p.add_argument("--out", default="trace_out",
                   help="output directory for the trace artifacts")
    p.add_argument("--profile-dir", default=None,
                   help="also capture a jax.profiler trace into this dir")
    a = p.parse_args(argv)

    import numpy as np

    from .ops.telemetry import TelemetryParams
    from .runtime.campaign import attack_gossipsub
    from .runtime.profiling import chrome_trace, profiler_trace
    from .runtime.simulator import ExperimentConfig, Simulator
    from .runtime.summarize import sanitize_nonfinite

    cfg = ExperimentConfig(
        topo=TopoParams(network_size=a.network_size, anchor_stages=5,
                        min_bandwidth=50, max_bandwidth=150,
                        min_latency=40, max_latency=130),
        connect_to=a.connect_to,
        # armed score params: the recorder's score quantiles / graylist
        # fraction measure nothing against the compiled-out default weights
        gossipsub=attack_gossipsub(),
        warmup_s=0.0,
        seed=a.seed,
    )
    sim = Simulator(cfg)
    hb_ms = float(sim.params.heartbeat_ms)
    tp = TelemetryParams(record=True, degree_bins=a.degree_bins)
    tp.validate()
    with profiler_trace(a.profile_dir):
        sim.advance(a.warmup_hb * hb_ms)      # untraced warmup
        sim.record_telemetry(tp)
        t0_ms = float(np.asarray(sim.state.t_ms))
        sim.advance(a.heartbeats * hb_ms)     # the recorded window
    tel = sim.last_telemetry
    if not tel:
        print("flight recorder produced no rounds "
              "(heartbeats too small for the heartbeat interval?)",
              file=sys.stderr)
        return 1

    os.makedirs(a.out, exist_ok=True)
    ct = chrome_trace(tel, hb_ms, t0_ms=t0_ms,
                      name=f"gossipsub n={a.network_size} seed={a.seed}")
    trace_path = os.path.join(a.out, "trace.perfetto.json")
    with open(trace_path, "w") as fh:
        json.dump(sanitize_nonfinite(ct), fh, allow_nan=False)
    npz_path = os.path.join(a.out, "rounds.npz")
    with open(npz_path, "wb") as fh:
        np.savez_compressed(fh, **{k: np.asarray(v) for k, v in tel.items()})
    # CSV: one row per heartbeat, vector channels expanded per index
    cols = []
    for k in sorted(tel):
        arr = np.asarray(tel[k])
        if arr.ndim == 1:
            cols.append((k, arr))
        else:
            cols.extend((f"{k}_{j}", arr[:, j]) for j in range(arr.shape[1]))
    steps = int(cols[0][1].shape[0])
    csv_path = os.path.join(a.out, "rounds.csv")
    with open(csv_path, "w") as fh:
        fh.write("hb," + ",".join(k for k, _ in cols) + "\n")
        for i in range(steps):
            fh.write(f"{i}," + ",".join(
                format(float(v[i]), "g") for _, v in cols) + "\n")

    cov = np.asarray(tel["tel_mesh_coverage"])
    hits = np.nonzero(cov >= 0.9)[0]
    summary = {
        "network_size": a.network_size,
        "heartbeats": steps,
        "heartbeat_ms": hb_ms,
        "channels": sorted(tel),
        "coverage90_hb": int(hits[0]) + 1 if hits.size else -1,
        "final_mean_degree": float(np.asarray(tel["tel_mean_degree"])[-1]),
        "trace_json": trace_path,
        "rounds_npz": npz_path,
        "rounds_csv": csv_path,
        "profile_dir": a.profile_dir,
    }
    print(json.dumps(sanitize_nonfinite(summary), indent=2, allow_nan=False))
    return 0


def cmd_summarize(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="summarize")
    p.add_argument("path")
    p.add_argument("--large", action="store_true")
    a = p.parse_args(argv)
    from .runtime.summarize import report, summarize_file

    print(report(summarize_file(a.path, large=a.large), large=a.large), end="")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]
    backend = env_str("SIMBACKEND", "tpu")
    platform = env_str("SIMPLATFORM", "")
    if platform and cmd not in ("topogen", "summarize"):
        # pin the JAX platform before any backend initializes (e.g.
        # SIMPLATFORM=cpu for small role-based runs where an accelerator's
        # first-compile latency dominates). config.update is authoritative
        # even when an environment sitecustomize pre-imported jax. topogen/
        # summarize are pure numpy — don't pay the jax import for them.
        import jax

        jax.config.update("jax_platforms", platform)
    if cmd == "topogen":
        return cmd_topogen(rest)
    if cmd == "run":
        if backend.lower() not in ("tpu", "jax"):
            print(
                f"SIMBACKEND={backend} is not provided by this package "
                "(use the reference's shadow/ harness for the shadow backend)",
                file=sys.stderr,
            )
            return 2
        return cmd_run(rest)
    if cmd == "summarize":
        return cmd_summarize(rest)
    if cmd == "serve":
        return cmd_serve(rest)
    if cmd == "attack":
        return cmd_attack(rest)
    if cmd == "pareto":
        return cmd_pareto(rest)
    if cmd == "arena":
        return cmd_arena(rest)
    if cmd == "inject":
        return cmd_inject(rest)
    if cmd == "kad":
        return cmd_kad(rest)
    if cmd == "connmanager":
        return cmd_connmanager(rest)
    if cmd == "servicedisco":
        return cmd_servicedisco(rest)
    if cmd == "regression":
        return cmd_regression(rest)
    if cmd == "lint":
        return cmd_lint(rest)
    if cmd == "conform":
        return cmd_conform(rest)
    if cmd == "trace":
        return cmd_trace(rest)
    if cmd == "microbench":
        return cmd_microbench(rest)
    print(f"unknown command: {cmd}\n{__doc__}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
