"""Native acceleration surfaces: the C++ log-emitter sources (liblogemit.so,
loaded by runtime/native_logemit.py) and the Pallas VMEM-gather kernel
(vmem_gather.py) behind its runtime capability probe."""
