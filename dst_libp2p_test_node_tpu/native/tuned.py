"""Autotuned kernel block-size table (ISSUE 16, arXiv:1912.03413 style).

The microbench harness (runtime/microbench.py) sweeps each Pallas kernel's
row-block candidates and writes the winners to a strict-JSON `tuned.json`:

    {"vmem_gather": {"block_rows": 256}, "score_update": {"block_rows": 128}}

The kernels' block choosers consult this table before falling back to the
built-in largest-dividing-power-of-two heuristic. The table is OPTIONAL and
advisory: a missing file, malformed entry, or a block that does not tile
the requested row count exactly is ignored (the heuristic answer is always
valid), so shipping no table — the default — changes nothing. The search
path is `DST_TUNED_JSON` when set, else `tuned.json` next to this module
(where `microbench --install` writes it).
"""

from __future__ import annotations

import functools
import json
import os

_ENV = "DST_TUNED_JSON"


def tuned_path() -> str:
    return (os.environ.get(_ENV)
            or os.path.join(os.path.dirname(__file__), "tuned.json"))


@functools.cache
def _load() -> dict:
    try:
        with open(tuned_path()) as fh:
            table = json.load(fh)
        return table if isinstance(table, dict) else {}
    except (OSError, ValueError):
        return {}


def invalidate_cache() -> None:
    """Drop the cached table (microbench re-reads after writing it)."""
    _load.cache_clear()


def tuned_block_rows(kernel: str, n_rows: int, max_block: int) -> int | None:
    """The tuned row block for `kernel`, or None when the table has no
    usable entry. Usable = a positive int that tiles n_rows exactly and
    respects the kernel's VMEM ceiling — anything else falls back to the
    caller's heuristic rather than producing an invalid grid."""
    entry = _load().get(kernel)
    if not isinstance(entry, dict):
        return None
    block = entry.get("block_rows")
    if (isinstance(block, int) and not isinstance(block, bool)
            and 0 < block <= max_block and n_rows % block == 0):
        return block
    return None
