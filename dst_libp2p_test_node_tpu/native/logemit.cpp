// Native latency-line emitter (loaded via ctypes, see runtime/native_logemit.py).
//
// Formats one message's worth of awk-consumable latencies lines:
//   shadow.data/hosts/peer<pid>/main.1000.stdout:<lineno>:<msgId> milliseconds: <ms>
// The reference gets these lines for free from grep over per-process stdout
// files (shadow/run.sh:61); with a million simulated peers in one process,
// Python string formatting becomes the bottleneck, hence this C++ hot path
// (SURVEY.md §2 native-component note).
//
// Build: g++ -O2 -shared -fPIC -o liblogemit.so logemit.cpp

#include <cstdint>
#include <cstring>

namespace {

// fast unsigned integer -> ascii, returns chars written
inline int u64_to_ascii(uint64_t v, char *out) {
  char tmp[20];
  int n = 0;
  do {
    tmp[n++] = static_cast<char>('0' + (v % 10));
    v /= 10;
  } while (v != 0);
  for (int i = 0; i < n; ++i) out[i] = tmp[n - 1 - i];
  return n;
}

inline int i64_to_ascii(int64_t v, char *out) {
  if (v < 0) {
    out[0] = '-';
    return 1 + u64_to_ascii(static_cast<uint64_t>(-v), out + 1);
  }
  return u64_to_ascii(static_cast<uint64_t>(v), out);
}

constexpr char kPrefix[] = "shadow.data/hosts/peer";
constexpr char kStdout[] = "/main.1000.stdout:";
constexpr char kMillis[] = " milliseconds: ";

}  // namespace

extern "C" {

// Returns bytes written, or -1 if the output buffer is too small.
long long format_block(unsigned long long msg_id, const long long *peers,
                       const long long *linenos, const long long *delays,
                       long long count, char *out, long long capacity) {
  char msg_buf[21];
  const int msg_len = u64_to_ascii(msg_id, msg_buf);
  char *p = out;
  const char *end = out + capacity;
  // worst case line: 57 fixed chars + 3x21-char signed int64 + 20-char msgId
  for (long long i = 0; i < count; ++i) {
    if (end - p < 160) return -1;
    std::memcpy(p, kPrefix, sizeof(kPrefix) - 1);
    p += sizeof(kPrefix) - 1;
    p += i64_to_ascii(peers[i], p);
    std::memcpy(p, kStdout, sizeof(kStdout) - 1);
    p += sizeof(kStdout) - 1;
    p += i64_to_ascii(linenos[i], p);
    *p++ = ':';
    std::memcpy(p, msg_buf, msg_len);
    p += msg_len;
    std::memcpy(p, kMillis, sizeof(kMillis) - 1);
    p += sizeof(kMillis) - 1;
    p += i64_to_ascii(delays[i], p);
    *p++ = '\n';
  }
  return p - out;
}

}  // extern "C"
