"""Pallas VMEM-gather kernel for the receiver-side fixpoint — the PARITY
"Known gaps" retry, behind a runtime capability probe.

The hot gather of the receiver-side formulation (parallel/exchange._inc_from)
is `t_all[src]`: an (N, C) int32 index into the (N,) f32 arrival-time vector,
once per fixpoint iteration. XLA lowers it as a generic dynamic-gather that
re-streams from HBM; the whole t vector is tiny (400 KB at 100k peers, 4 MB
at 1M — comfortably inside one core's ~16 MB VMEM), so the kernel here pins
it VMEM-resident for the entire row sweep and gathers each row block against
it with a single vectorized take.

An earlier attempt (PARITY "Known gaps") was blocked by the then-current
Mosaic toolchain: no vectorized VMEM gather, and the scalar-store/scalar-loop
workarounds crashed the compiler. Whether THIS formulation compiles is
therefore decided at runtime by `gather_kernel_available()`: a one-shot
cached probe that compiles and runs a miniature instance (including under
vmap — the fragment axis vmaps the callers) and compares it against the
plain-XLA reference. Any failure — import error, Mosaic rejection, wrong
numerics — makes the probe False and callers keep the receiver-side
constant formulation unchanged, so CPU CI and older toolchains stay green
by construction. `DST_PALLAS_GATHER=0` forces the kernel off (bench A/B
isolation); `=1` forces the probe to raise instead of degrade (debugging a
toolchain where it SHOULD work).

CPU correctness of the kernel body itself is tested with `interpret=True`
(tests/test_exact_prefix.py), which runs the Pallas program without Mosaic.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .tuned import tuned_block_rows

_ENV = "DST_PALLAS_GATHER"

# largest row-block whose int32 index + f32 output tiles stay a small
# fraction of VMEM next to the resident t vector (8 * C * 8 bytes per
# 8-row step; 512 rows x 64 slots = 256 KB of tiles)
_MAX_BLOCK = 512


def _block_rows(n_rows: int) -> int:
    """The microbench autotuner's tuned.json block when it has a valid
    entry (native/tuned.py), else the largest power-of-two row block
    <= _MAX_BLOCK dividing n_rows (grid steps must tile the array exactly;
    every simulator shape is a round number, and a worst-case odd N just
    runs block=1 under interpret in tests — the probe rejects it for the
    real kernel)."""
    tuned = tuned_block_rows("vmem_gather", n_rows, _MAX_BLOCK)
    if tuned is not None:
        return tuned
    b = 1
    while b < _MAX_BLOCK and n_rows % (b * 2) == 0:
        b *= 2
    return b


@functools.cache
def _compiled(n_rows: int, cap: int, n_src: int, interpret: bool,
              block_rows: int | None = None):
    """Build the pallas_call for one (rows, cap, src-len) shape. Raises
    whatever Pallas/Mosaic raises — callers go through the probe.
    `block_rows` overrides the tuned/heuristic block (the microbench
    sweep's knob); it must tile n_rows exactly."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = block_rows if block_rows is not None else _block_rows(n_rows)
    if n_rows % block != 0:
        raise ValueError(f"block_rows {block} does not tile {n_rows} rows")
    if not interpret and block < 8:
        # sub-tile row blocks can't meet the (8, 128) f32 tiling floor
        raise ValueError(f"row count {n_rows} leaves block {block} < 8")

    def kernel(t_ref, idx_ref, out_ref):
        # the whole t vector is VMEM-resident (index_map pins block 0 for
        # every grid step); one vectorized take per row block
        idx = idx_ref[...]
        out_ref[...] = jnp.take(t_ref[...], idx.reshape(-1),
                                axis=0).reshape(idx.shape)

    return pl.pallas_call(
        kernel,
        grid=(n_rows // block,),
        in_specs=[
            pl.BlockSpec((n_src,), lambda i: (0,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, cap), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, cap), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_rows, cap), jnp.float32),
        interpret=interpret,
    )


def vmem_gather(t_all: jnp.ndarray, src: jnp.ndarray, *,
                interpret: bool = False,
                block_rows: int | None = None) -> jnp.ndarray:
    """out[q, j] = t_all[max(src[q, j], 0)] via the VMEM-resident kernel.
    Same clip-negative-to-0 convention as the XLA fallback (pad slots are
    masked by the caller's validity flags, so row 0's value is dead
    there). `block_rows` is the microbench sweep's explicit row-block
    override; production callers leave it None (tuned.json/heuristic)."""
    idx = jnp.clip(src, 0)
    return _compiled(src.shape[0], src.shape[1], t_all.shape[0],
                     interpret, block_rows)(t_all.astype(jnp.float32), idx)


def _probe() -> bool:
    """Compile + run a miniature instance on the real backend (plus one
    vmapped application — the fragment axis vmaps the callers) and check
    it against plain XLA. True only if everything compiles AND matches."""
    if jax.default_backend() != "tpu":
        # the kernel exists to exploit TPU VMEM; interpret mode on CPU is
        # a test vehicle, not a win
        return False
    try:
        n, c = 256, 8
        t = jnp.arange(n, dtype=jnp.float32) * 0.5
        src = (jnp.arange(n * c, dtype=jnp.int32).reshape(n, c) * 7) % n
        src = src.at[0, 0].set(-1)
        want = t[jnp.clip(src, 0)]
        got = jax.jit(vmem_gather)(t, src)
        if not bool(jnp.all(got == want)):
            return False
        got_v = jax.jit(jax.vmap(vmem_gather, in_axes=(None, 0)))(
            t, jnp.stack([src, (src + 1) % n]))
        want_v = jnp.stack([want, t[(src + 1) % n]])
        return bool(jnp.all(got_v == want_v))
    except Exception:  # noqa: BLE001 - ANY failure means "not available"
        return False


@functools.cache
def gather_kernel_available() -> bool:
    """One-shot cached capability verdict. Env override DST_PALLAS_GATHER:
    "0" forces off, "1" runs the probe but RAISES on failure (so a
    toolchain where the kernel should work can't silently degrade)."""
    env = os.environ.get(_ENV, "")
    if env == "0":
        return False
    ok = _probe()
    if env == "1" and not ok:
        raise RuntimeError(
            "DST_PALLAS_GATHER=1 but the VMEM-gather probe failed "
            "(backend not TPU, Mosaic rejected the kernel, or numerics "
            "mismatched)")
    return ok
