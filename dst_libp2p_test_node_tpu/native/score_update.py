"""Pallas fused scoring-update kernel, behind a runtime capability probe.

The heartbeat scan defers the per-round counter decay into two carried
scalars and materializes it once post-scan (ops/heartbeat._apply_decay on
`fmd` and `slow_penalty`), after which every consumer immediately re-reads
the decayed counters through SimState.score — a second full (N, C) HBM
round-trip for a few flops. This kernel fuses the two: one pass over the
row blocks applies both decays (with the flush-to-zero floor) AND emits the
weighted score, so the counters stream through VMEM exactly once.

Same discipline as native/vmem_gather.py, the first kernel behind this
pattern: whether the Mosaic toolchain compiles THIS formulation is decided
at runtime by `score_kernel_available()` — a one-shot cached probe that
compiles a miniature instance on the real backend and compares it against
the plain-XLA reference (`score_update_xla`, which is bit-for-bit the
heartbeat/_apply_decay + SimState.score composition). Any failure makes the
probe False and callers keep the XLA formulation, so CPU CI and older
toolchains stay green by construction. `DST_PALLAS_SCORE=0` forces the
kernel off; `=1` forces the probe to raise instead of degrade.

CPU correctness of the kernel body itself is tested with `interpret=True`
(tests/test_score_kernel.py), which runs the Pallas program without Mosaic.
The row-block size consults the microbench autotuner's tuned.json
(native/tuned.py) before the largest-dividing-power-of-two heuristic.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .tuned import tuned_block_rows

_ENV = "DST_PALLAS_SCORE"

# three f32 (block, C) tiles live per grid step (two counters in, score
# out, counters updated in place of their input tiles); 512 rows x 64
# slots x 5 arrays = 640 KB — a small fraction of a core's ~16 MB VMEM
_MAX_BLOCK = 512


def _block_rows(n_rows: int) -> int:
    """Tuned row block when tuned.json has a valid entry, else the largest
    power-of-two <= _MAX_BLOCK dividing n_rows (grid steps must tile the
    array exactly)."""
    tuned = tuned_block_rows("score_update", n_rows, _MAX_BLOCK)
    if tuned is not None:
        return tuned
    b = 1
    while b < _MAX_BLOCK and n_rows % (b * 2) == 0:
        b *= 2
    return b


@functools.cache
def _compiled(n_rows: int, cap: int, fmd_weight: float, slow_weight: float,
              fmd_cap: float, decay_to_zero: float, interpret: bool,
              block_rows: int | None = None):
    """Build the pallas_call for one (rows, cap) shape + weight constants.
    Raises whatever Pallas/Mosaic raises — callers go through the probe.
    `block_rows` overrides the tuned/heuristic block (the microbench
    sweep's knob); it must tile n_rows exactly."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = block_rows if block_rows is not None else _block_rows(n_rows)
    if n_rows % block != 0:
        raise ValueError(f"block_rows {block} does not tile {n_rows} rows")
    if not interpret and block < 8:
        # sub-tile row blocks can't meet the (8, 128) f32 tiling floor
        raise ValueError(f"row count {n_rows} leaves block {block} < 8")

    def kernel(sc_ref, fmd_ref, slow_ref, fmd_out, slow_out, score_out):
        # the (2,) decay-scale vector is VMEM-resident for every grid step
        sc = sc_ref[...]
        f = fmd_ref[...] * sc[0]
        s = slow_ref[...] * sc[1]
        f = jnp.where(f < decay_to_zero, 0.0, f)
        s = jnp.where(s < decay_to_zero, 0.0, s)
        fmd_out[...] = f
        slow_out[...] = s
        score_out[...] = (fmd_weight * jnp.minimum(f, fmd_cap)
                          + slow_weight * s)

    row_spec = pl.BlockSpec((block, cap), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(n_rows // block,),
        in_specs=[
            pl.BlockSpec((2,), lambda i: (0,), memory_space=pltpu.VMEM),
            row_spec,
            row_spec,
        ],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((n_rows, cap), jnp.float32)] * 3,
        interpret=interpret,
    )


def score_update(fmd, slow_penalty, f_scale, s_scale, params, *,
                 interpret: bool = False, block_rows: int | None = None):
    """(decayed fmd, decayed slow_penalty, score) in one fused pass.

    `f_scale`/`s_scale` are the heartbeat scan's carried decay scalars
    (traced); the weight/cap/flush constants come from `params` and bake
    into the compiled kernel like every other SimParams static.
    `block_rows` is the microbench sweep's explicit row-block override;
    production callers leave it None (tuned.json/heuristic)."""
    scales = jnp.stack([jnp.asarray(f_scale, jnp.float32),
                        jnp.asarray(s_scale, jnp.float32)])
    return _compiled(
        fmd.shape[0], fmd.shape[1], float(params.fmd_weight),
        float(params.slow_weight), float(params.fmd_cap),
        float(params.decay_to_zero), interpret, block_rows,
    )(scales, fmd.astype(jnp.float32), slow_penalty.astype(jnp.float32))


def score_update_best(fmd, slow_penalty, f_scale, s_scale, params):
    """The dispatch point consumers call (parallel/exchange._src_gather's
    routing pattern): the Pallas kernel when the one-shot capability probe
    passes on this backend, the plain-XLA formulation everywhere else."""
    if score_kernel_available():
        return score_update(fmd, slow_penalty, f_scale, s_scale, params)
    return score_update_xla(fmd, slow_penalty, f_scale, s_scale, params)


def score_update_xla(fmd, slow_penalty, f_scale, s_scale, params):
    """The plain-XLA reference and fallback: literally the
    ops/heartbeat._apply_decay composition followed by SimState.score, so
    the kernel's correctness target IS the production formula."""
    f = fmd * f_scale
    s = slow_penalty * s_scale
    f = jnp.where(f < params.decay_to_zero, 0.0, f)
    s = jnp.where(s < params.decay_to_zero, 0.0, s)
    score = (params.fmd_weight * jnp.minimum(f, params.fmd_cap)
             + params.slow_weight * s)
    return f, s, score


def _probe() -> bool:
    """Compile + run a miniature instance on the real backend and check it
    against the XLA reference. True only if everything compiles AND the
    counters match bitwise (the score read carries an ulp-level FMA
    tolerance)."""
    if jax.default_backend() != "tpu":
        # the kernel exists to exploit TPU VMEM; interpret mode on CPU is
        # a test vehicle, not a win
        return False
    try:
        from ..ops.state import SimParams

        n, c = 256, 8
        params = SimParams(n=n, capacity=c, slow_weight=-10.0)
        fmd = (jnp.arange(n * c, dtype=jnp.float32).reshape(n, c) % 13) * 0.3
        slow = (jnp.arange(n * c, dtype=jnp.float32).reshape(n, c) % 7) * 0.2
        want = score_update_xla(fmd, slow, 0.9, 0.8, params)
        got = jax.jit(functools.partial(score_update, params=params))(
            fmd, slow, 0.9, 0.8)
        # the carried counters must come back bit-for-bit; the weighted
        # score read tolerates a few ulp of FMA contraction — the same
        # class of difference XLA's own fusion choices introduce between
        # jitted and eager evaluations of the reference formula
        if not (bool(jnp.all(got[0] == want[0]))
                and bool(jnp.all(got[1] == want[1]))):
            return False
        return bool(jnp.allclose(got[2], want[2], rtol=1e-5, atol=1e-6))
    except Exception:  # noqa: BLE001 - ANY failure means "not available"
        return False


@functools.cache
def score_kernel_available() -> bool:
    """One-shot cached capability verdict. Env override DST_PALLAS_SCORE:
    "0" forces off, "1" runs the probe but RAISES on failure (so a
    toolchain where the kernel should work can't silently degrade)."""
    env = os.environ.get(_ENV, "")
    if env == "0":
        return False
    ok = _probe()
    if env == "1" and not ok:
        raise RuntimeError(
            "DST_PALLAS_SCORE=1 but the scoring-update probe failed "
            "(backend not TPU, Mosaic rejected the kernel, or numerics "
            "mismatched)")
    return ok
