from .sharding import make_peer_mesh, shard_simulation, peer_sharding, replicated  # noqa: F401
