"""Hand-tuned cross-shard exchange for the dissemination fixpoint.

The reference's cross-peer traffic is TCP/QUIC sockets between processes;
sharded across TPU chips, a mesh edge whose endpoints live on different
shards must move data over ICI (SURVEY.md §2 parallelism table). The naive
formulation (ops/disseminate.py's sender-side `offers` + `pull`) reads the
full (N, C) candidate matrix across shards every fixpoint iteration; under
XLA auto-partitioning that becomes repeated all-gathers of C floats per peer.

This module reformulates the fixpoint receiver-side so the ONLY cross-shard
value is the (N,) arrival-time vector — 4 bytes/peer/iteration over ICI:

    inc[q, j] = t_rx[p] + A[q, j]                          (mesh edges)
    inc[q, j] = nextHB(t_rx[p] + proc, phase[p]) + G[q, j] (gossip edges)
    t_rx'[q]  = min(t_rx[q], min_j inc[q, j])     with p = conns[q, j]

where A and G are per-edge constants (uplink-serialization rank, stage
latency, tx time) gathered ONCE through the reverse-slot map before the
loop. Both the everything-on-one-shard path and the `shard_map` path run the
same expression; the sharded variant all-gathers t_rx and psums the
convergence flag, so XLA emits exactly one small collective pair per
iteration — the ICI-riding design the scaling recipe calls for (mesh ->
shardings -> let XLA insert collectives).

Equivalence to the sender-side formulation is exact: offers are affine in
the sender's arrival time for mesh edges, and the gossip term only needs
t_rx[p] and the sender's heartbeat phase (see test_exchange.py).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import shard_map as _shard_map  # jax-version compat resolver

INF = jnp.float32(3.4e38)

PEER_AXIS = "peers"


@struct.dataclass
class RecvConstants:
    """Per-receiver-slot constants of one fixpoint (fragment x phase).

    The fixpoint carry is memory-bound (ARCHITECTURE §6): every iteration
    streams these tables from HBM, so their byte width IS the iteration
    cost at the 1M-peer shapes this formulation exists for. Two layout
    decisions follow. (1) The two validity masks are packed into one int8
    `flags` word per slot (bit 0 mesh, bit 1 gossip) — half the bool
    traffic, bit-identical results. (2) With `packed=True` at build time
    (SimParams.packed_state), the per-edge RELATIVE cost tables
    (a_ms/g_ms/g_off/phase — values span a few thousand ms) are stored
    bf16 and upcast in _inc_from, halving their traffic at a worst-case
    quantization of ~2 ms per edge (bf16's 8-bit mantissa at the ~200 ms
    edge scale), inside the bounded mode's exported error bar. The
    ABSOLUTE-time fields (u_ms, rx_c, and the t vector itself) and the
    accounting fold stay f32 unconditionally: the sim clock runs to ~1e6
    ms, where a bf16 ulp is ~4 s."""

    src: jnp.ndarray        # (N, C) int32 sender peer id (conns), -1 pad
    a_ms: jnp.ndarray       # (N, C) f32/bf16 mesh-edge additive constant
    #                         (queue slot + latency; proc applies to the start)
    g_ms: jnp.ndarray       # (N, C) f32/bf16 gossip additive constant
    g_off: jnp.ndarray      # (N, C) f32/bf16 gossip-round heartbeat offset:
    #                         the mcache window re-samples IHAVE targets each
    #                         heartbeat; this is (first round sampled) * hb_ms
    phase: jnp.ndarray      # (N, C) f32/bf16 sender heartbeat phase
    u_ms: jnp.ndarray       # (N, C) float32 sender uplink-free time: sends
    #                         start no earlier than this (cross-message
    #                         bandwidth contention, ops/state.py uplink_free_ms)
    flags: jnp.ndarray      # (N, C) int8 validity word: bit 0 = mesh edge
    #                         active, bit 1 = gossip edge active
    rx_c: jnp.ndarray       # (N,) float32 receiver downlink clamp: delivery
    #                         completes no earlier than this (rx_free + rx_ms,
    #                         ops/state.py rx_free_ms) — receiver-local, so it
    #                         shards with the rows
    proc_ms: jnp.ndarray    # () float32
    hb_ms: jnp.ndarray      # () float32


def _edge_gather(sender_val: jnp.ndarray, conns: jnp.ndarray,
                 rev: jnp.ndarray) -> jnp.ndarray:
    """recv[q, j] = sender_val[conns[q,j], rev[q,j]] (one-time gather)."""
    return sender_val[jnp.clip(conns, 0), jnp.clip(rev, 0)]


def build_recv_constants(
    conns: jnp.ndarray,
    rev: jnp.ndarray,
    lat_edge: jnp.ndarray,      # (N, C) sender-side per-slot latency
    tx_ms: jnp.ndarray,         # (N,) sender uplink ms per fragment
    rank: jnp.ndarray,          # (N, C) sender-side send order
    k_p: jnp.ndarray,           # (N,) sender fanout size
    frag_idx,
    send_mask: jnp.ndarray,     # (N, C) sender-side forwarding mask
    can_send: jnp.ndarray,      # (N,) alive & subscribed
    g_tgt: jnp.ndarray,         # (N, C) sender-side gossip targets (any round)
    g_off_s: jnp.ndarray,       # (N, C) sender-side gossip-round offset (ms)
    hb_phase: jnp.ndarray,      # (N,) heartbeat phase
    uplink_free: jnp.ndarray,   # (N,) sender uplink-free time (absolute ms)
    rx_const: jnp.ndarray,      # (N,) receiver downlink clamp (rx_free + rx_ms)
    proc_ms: float,
    hb_ms: float,
    with_gossip: bool,
    lat_deliver=None,
    ld_gossip=None,
    packed: bool = False,
) -> RecvConstants:
    """Gather every sender-side term of ops/disseminate.offers through the
    reverse-slot map once, leaving a fixpoint that touches only t_rx.

    `lat_deliver` / `ld_gossip`: optional (N, C) effective DELIVERY latency
    of the data-carrying traversal for mesh sends / gossip answers — wire
    latency scaled by the TCP slow-start flight count plus the sampled
    retransmission stall (ops/disseminate loss_mode="tcp"). Additive edge
    constants, so they fold into a_ms/g_ms here and cost the fixpoint
    nothing per iteration. Default to the bare lat_edge.

    `packed`: store the relative cost tables bf16 (see RecvConstants) —
    the unpacked build is the reference path the packed one is
    tolerance-pinned against (tests/test_exchange.py)."""
    valid = (conns >= 0) & (rev >= 0)
    queue = (rank + 1.0 + frag_idx * k_p[:, None]) * tx_ms[:, None]
    if lat_deliver is None:
        lat_deliver = lat_edge
    if ld_gossip is None:
        ld_gossip = lat_deliver
    a_sender = queue + lat_deliver  # offers minus the send start
    a_ms = jnp.where(valid, _edge_gather(a_sender, conns, rev), INF)
    mesh_ok = valid & _edge_gather(
        send_mask & can_send[:, None], conns, rev)

    if with_gossip:
        g_sender = 2.0 * lat_edge + ld_gossip + tx_ms[:, None]
        g_ms = jnp.where(valid, _edge_gather(g_sender, conns, rev), INF)
        g_ok = valid & _edge_gather(g_tgt & can_send[:, None], conns, rev)
        g_off = _edge_gather(g_off_s, conns, rev)
    else:
        g_ms = jnp.full_like(a_ms, INF)
        g_ok = jnp.zeros_like(mesh_ok)
        g_off = jnp.zeros_like(a_ms)
    phase = _edge_gather(
        jnp.broadcast_to(hb_phase[:, None], conns.shape), conns, rev)
    u_ms = _edge_gather(
        jnp.broadcast_to(uplink_free[:, None], conns.shape), conns, rev)
    # relative cost tables only: bf16's exponent range carries the INF
    # sentinel through as inf, and _inc_from's flag masks make the pad
    # values dead anyway
    store = ((lambda x: x.astype(jnp.bfloat16)) if packed
             else (lambda x: x))
    return RecvConstants(
        src=jnp.where(valid, conns, -1),
        a_ms=store(a_ms),
        g_ms=store(g_ms),
        g_off=store(g_off),
        phase=store(phase),
        u_ms=u_ms,
        flags=(mesh_ok.astype(jnp.int8)
               | (g_ok.astype(jnp.int8) << 1)),
        rx_c=jnp.asarray(rx_const, jnp.float32),
        proc_ms=jnp.float32(proc_ms),
        hb_ms=jnp.float32(hb_ms),
    )


def _src_gather(t_all: jnp.ndarray, src: jnp.ndarray) -> jnp.ndarray:
    """The fixpoint's hot gather: t of every slot's sender. Routed through
    the Pallas VMEM-resident kernel when the one-shot capability probe
    passes on this backend (native/vmem_gather.py — the t vector stays
    VMEM-pinned across the row sweep instead of re-streaming per block);
    otherwise the plain XLA gather. Negative src marks pad slots; both
    paths clip them to row 0, whose value is dead behind the flag masks."""
    from ..native.vmem_gather import gather_kernel_available, vmem_gather

    if gather_kernel_available():
        return vmem_gather(t_all, src)
    return t_all[jnp.clip(src, 0)]


def _inc_from(t_all: jnp.ndarray, c: RecvConstants) -> jnp.ndarray:
    """Incoming offers of every receiver slot given the global t_rx.
    Upcasts the (possibly bf16-packed) relative cost tables to f32 at the
    registers — the arithmetic and the returned matrix are f32 either way;
    packing only changes what streams from HBM."""
    t_src = _src_gather(t_all, c.src)
    live = (c.src >= 0) & (t_src < INF)
    mesh_ok = (c.flags & 1) > 0
    g_ok = (c.flags & 2) > 0
    a_ms = c.a_ms.astype(jnp.float32)
    g_ms = c.g_ms.astype(jnp.float32)
    g_off = c.g_off.astype(jnp.float32)
    phase = c.phase.astype(jnp.float32)
    base = t_src + c.proc_ms
    # a sender's queue can't start before its uplink drains earlier traffic
    start = jnp.maximum(base, c.u_ms)
    inc = jnp.where(mesh_ok & live, start + a_ms, INF)
    hb = (jnp.floor((base - phase) / c.hb_ms) + 1.0) * c.hb_ms + phase
    inc_g = jnp.where(
        g_ok & live, jnp.maximum(hb + g_off, c.u_ms) + g_ms, INF)
    # min with the sentinel: packed builds round INF up to bf16 inf, and
    # inf-tainted arithmetic must not leak past the f32 sentinel the
    # fixpoint (and strict-JSON export) reasons in
    return jnp.minimum(jnp.minimum(inc, inc_g), INF)


def converge_recv(
    t0: jnp.ndarray, c: RecvConstants, max_iters: int, g_floor=None
):
    """Single-shard receiver-side fixpoint (reference for the sharded one).

    `g_floor`: optional (N,) per-receiver FROZEN gossip candidate — the
    serialized answer offers of one outer pass of the serialized-answer
    model (ops/disseminate gossip_serial), already row-minimized. Receiver-
    local, so it joins the row min at zero per-iteration cost.

    Returns (t_rx, inc, converged): the fixpoint, the (N, C) incoming-
    offer matrix of the loop's LAST pass (the no-change confirmation pass
    evaluates it at the final times, so it rides out for free — callers
    reuse it for first-sender attribution and for the warm-start
    undershoot certificate instead of paying another full pull), and the
    final change bit inverted (False only when the iteration cap cut the
    loop, in which case `inc` is one pass stale)."""

    def cond(carry):
        _, _, changed, it = carry
        return changed & (it < max_iters)

    def body(carry):
        t_rx, _, _, it = carry
        # downlink clamp: delivery completes no earlier than the receiver's
        # downlink drains prior traffic plus this copy (max distributes over
        # the row min, so clamping the min equals clamping every candidate)
        inc = _inc_from(t_rx, c)
        inc_min = inc.min(axis=-1)
        if g_floor is not None:
            inc_min = jnp.minimum(inc_min, g_floor)
        t_new = jnp.minimum(t_rx, jnp.maximum(inc_min, c.rx_c))
        return t_new, inc, jnp.any(t_new < t_rx), it + 1

    inc0 = jnp.full(c.src.shape, INF)
    # strong int32 counter: a Python-int carry is weak-typed (GA-J002)
    t_rx, inc, changed, _ = jax.lax.while_loop(
        cond, body, (t0, inc0, jnp.bool_(True), jnp.int32(0)))
    return t_rx, inc, ~changed


def converge_sharded(
    t0: jnp.ndarray, c: RecvConstants, max_iters: int, mesh: Mesh,
    g_floor=None, axis_name: str = PEER_AXIS,
):
    """shard_map fixpoint over the peer axis: rows of the constants live on
    their shard; each iteration all-gathers the (N,) time vector over ICI
    and psums one convergence bit. Identical results to converge_recv
    (including the optional frozen `g_floor`, which shards with the rows,
    and the carried-out (inc, converged) pair — inc rows shard like the
    constants; converged is replicated by the psum).

    `axis_name`: which mesh axis the rows partition over — PEER_AXIS on the
    1-D simulation mesh, or the peer axis of a nested trials x peers grid
    (parallel/sharding.make_trial_mesh), where the same body runs inside
    each trial group's submesh. `mesh` may carry other axes; only
    `axis_name` is mapped here, so any extra axes replicate."""
    rows = P(axis_name)
    use_floor = g_floor is not None
    if g_floor is None:
        g_floor = jnp.full_like(t0, INF)

    def local_fix(t0_l, src, a_ms, g_ms, g_off, phase, u_ms, flags,
                  rx_c, gf_l):
        c_l = RecvConstants(
            src=src, a_ms=a_ms, g_ms=g_ms, g_off=g_off, phase=phase,
            u_ms=u_ms, flags=flags, rx_c=rx_c,
            proc_ms=c.proc_ms, hb_ms=c.hb_ms,
        )

        def cond(carry):
            _, _, changed, it = carry
            return changed & (it < max_iters)

        def body(carry):
            t_l, _, _, it = carry
            t_all = jax.lax.all_gather(t_l, axis_name, tiled=True)
            inc = _inc_from(t_all, c_l)
            inc_min = inc.min(axis=-1)
            if use_floor:
                inc_min = jnp.minimum(inc_min, gf_l)
            t_new = jnp.minimum(t_l, jnp.maximum(inc_min, rx_c))
            changed = jax.lax.psum(
                jnp.any(t_new < t_l).astype(jnp.int32), axis_name) > 0
            return t_new, inc, changed, it + 1

        t_l, inc_l, changed, _ = jax.lax.while_loop(
            cond, body,
            (t0_l, jnp.full(src.shape, INF), jnp.bool_(True), jnp.int32(0)))
        return t_l, inc_l, ~changed

    fn = _shard_map(
        local_fix,
        mesh=mesh,
        in_specs=(rows,) * 10,
        out_specs=(rows, rows, P()),
    )
    return fn(t0, c.src, c.a_ms, c.g_ms, c.g_off, c.phase, c.u_ms,
              c.flags, c.rx_c, g_floor)


def place_sharded(mesh: Mesh, *arrays):
    """Put (N, ...) arrays row-sharded on the peer mesh (test harness +
    ad-hoc placement helper; the Simulator path uses sharding.shard_simulation)."""
    sh = NamedSharding(mesh, P(PEER_AXIS))
    out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]
