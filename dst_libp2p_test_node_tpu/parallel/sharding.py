"""Peer-axis sharding: the framework's scale-out story.

The reference scales by spawning more OS processes (one per peer) across
Shadow workers or K8s nodes; its cross-peer traffic rides TCP/QUIC sockets
(SURVEY.md §2 parallelism table). Here the peer axis IS the parallel axis:
every (N, ...) state array shards across TPU chips over a 1-D
`jax.sharding.Mesh` ("peers"), cross-shard mesh edges become XLA collectives
over ICI (gathers through the neighbor index arrays), and multi-host scales
the same mesh over DCN. This is the context-parallel analog the north star
asks for: the 1M-peer adjacency node-sharded across a v5e-8.

Latency/stage constants stay replicated (they are (S+1)^2-tiny); per-peer
rows shard on axis 0. XLA inserts the all-gathers for neighbor lookups; the
explicit shard_map + all_to_all bucketing lives in parallel/exchange.py for
the hand-tuned path.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map graduated from jax.experimental to the jax namespace in 0.6;
# resolve whichever this environment ships so the sharded paths run on both
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent import path
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _exp_shard_map

    # the 0.4.x experimental shard_map has no replication rule for
    # while_loop (the dissemination fixpoint carries one): disable the rep
    # check — out_specs still declare what is replicated, and the psums
    # inside the mapped bodies are what actually replicate it
    shard_map = _partial(_exp_shard_map, check_rep=False)


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host JAX run (DCN scale-out; SURVEY.md §2 'multi-pod via
    DCN'). Wraps jax.distributed.initialize: afterwards jax.devices() spans
    every host's chips and make_peer_mesh() builds the global peer mesh —
    per-iteration fixpoint collectives ride ICI within a slice and DCN
    across hosts, with no change to any engine code. Arguments default to
    the standard JAX env vars (JAX_COORDINATOR_ADDRESS etc.) / TPU metadata.
    Returns the process index."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index()


def make_peer_mesh(n_devices: int | None = None, platform: str | None = None) -> Mesh:
    """1-D peer mesh over the default backend's devices, or over a specific
    platform's (e.g. "cpu" to get the XLA_FLAGS-forced virtual host devices
    even when an accelerator plugin owns the default backend)."""
    devs = jax.devices(platform)
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("peers",))


def peer_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of any (N, ...) peer-major array shard across the mesh."""
    return NamedSharding(mesh, P("peers"))


TRIAL_AXIS = "trials"


def audit_trial_groups(n_devices: int | None = None) -> int:
    """Trial-group count the audit/registry mesh builders use.

    GRAFT_AUDIT_TRIAL_GROUPS overrides it so CI can trace every registered
    window contract on BOTH full-grid aspect ratios (2x4 and 4x2 under 8
    virtual devices) without touching the registry; the default is the
    2-group grid (2 x remaining-devices-per-group), degenerating to 1 on a
    single device. Must divide the device count evenly — same constraint
    make_trial_mesh enforces."""
    import os

    nd = len(jax.devices()) if n_devices is None else n_devices
    env = os.environ.get("GRAFT_AUDIT_TRIAL_GROUPS", "")
    if env:
        groups = int(env)
        if groups < 1 or nd % groups != 0:
            raise ValueError(
                f"GRAFT_AUDIT_TRIAL_GROUPS={groups} must divide the device "
                f"count {nd} evenly")
        return groups
    return 2 if nd >= 2 else 1


def make_trial_mesh(trial_groups: int | None = None,
                    n_devices: int | None = None,
                    platform: str | None = None) -> Mesh:
    """2-D trial x peer device grid for Monte-Carlo campaigns
    (runtime/campaign.py): axis 0 ("trials") partitions the (fraction, seed)
    sweep into independent device groups, axis 1 ("peers") partitions each
    group's peer row space. Both axes are live: the nested window programs
    (campaign.sharded_attack_window and friends) shard stacked trial state
    as P("trials", "peers") and the shared epoch-graph arrays as P("peers"),
    so with >1 peers per group each window body runs peer-partitioned under
    GSPMD instead of replicating the group's submesh. The default is still
    one device per group (trial_groups = all visible devices) — the right
    grid when trials outnumber devices; widen the peer axis (fewer groups)
    when the peer count, not the trial count, is the scale axis."""
    devs = jax.devices(platform)
    if n_devices is not None:
        devs = devs[:n_devices]
    if trial_groups is None:
        trial_groups = len(devs)
    if trial_groups < 1 or len(devs) % trial_groups != 0:
        raise ValueError(
            f"trial_groups {trial_groups} must divide the device count "
            f"{len(devs)} evenly")
    per_group = len(devs) // trial_groups
    grid = np.array(devs).reshape(trial_groups, per_group)
    return Mesh(grid, (TRIAL_AXIS, "peers"))


DCN_AXIS = "dcn"


def make_dcn_mesh(dcn: int | None = None,
                  trial_groups: int | None = None,
                  n_devices: int | None = None,
                  platform: str | None = None) -> Mesh:
    """Three-level dcn x trials x peers grid over the GLOBAL device set.

    The multi-host extension of make_trial_mesh (ROADMAP "go past one
    host"): axis 0 ("dcn") is PROCESS granularity — each dcn block is one
    host's addressable devices, so every "peers"-axis collective the nested
    window programs emit stays strictly inside a host's ICI submesh and
    only trial-axis work (which is embarrassingly parallel) ever spans the
    DCN boundary. Devices are ordered process-major (sorted by
    process_index) so dcn block b == process b's chips — the invariant the
    GA-S006 auditor's block classification and local_trial_submesh both
    rely on. `dcn` defaults to jax.process_count(); `trial_groups` is the
    PER-BLOCK trial-group count (defaults to 2 when the block has >= 2
    devices, mirroring audit_trial_groups)."""
    devs = jax.devices(platform)
    if n_devices is not None:
        devs = devs[:n_devices]
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    if dcn is None:
        dcn = jax.process_count()
    if dcn < 1 or len(devs) % dcn != 0:
        raise ValueError(
            f"dcn {dcn} must divide the device count {len(devs)} evenly")
    per_block = len(devs) // dcn
    if trial_groups is None:
        trial_groups = 2 if per_block >= 2 else 1
    if trial_groups < 1 or per_block % trial_groups != 0:
        raise ValueError(
            f"trial_groups {trial_groups} must divide the per-block device "
            f"count {per_block} evenly")
    grid = np.array(devs).reshape(dcn, trial_groups, per_block // trial_groups)
    if dcn == jax.process_count() > 1:
        for b in range(dcn):
            procs = {d.process_index for d in grid[b].flat}
            if len(procs) != 1:
                raise ValueError(
                    f"dcn block {b} spans processes {sorted(procs)}; the "
                    f"DCN axis must be process granularity (peer collectives "
                    f"would cross the DCN boundary)")
    return Mesh(grid, (DCN_AXIS, TRIAL_AXIS, "peers"))


def local_trial_submesh(mesh: Mesh) -> Mesh:
    """This process's 2-D trials x peers submesh of a make_dcn_mesh grid.

    The runtime half of the DCN split: the campaign executes the SAME
    jitted nested window per process on its addressable block (supervisor
    retries, checkpoints, and recovery legs stay process-local), while the
    3-level mesh exists for placement reasoning and the static GA-S006
    audit. On a mesh without a dcn axis this is the identity."""
    if DCN_AXIS not in mesh.axis_names:
        return mesh
    rank = jax.process_index()
    grid = mesh.devices
    for b in range(grid.shape[0]):
        if all(d.process_index == rank for d in grid[b].flat):
            return Mesh(grid[b], (TRIAL_AXIS, "peers"))
    raise ValueError(
        f"no dcn block of {mesh} is wholly addressable by process {rank}")


def trial_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (stacked-trial) sharding over a make_trial_mesh grid;
    on a 3-level make_dcn_mesh grid the stacked axis splits over dcn AND
    trial groups (dcn-major, matching the seed round-robin)."""
    if DCN_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P((DCN_AXIS, TRIAL_AXIS)))
    return NamedSharding(mesh, P(TRIAL_AXIS))


def nested_sharding(mesh: Mesh) -> NamedSharding:
    """Both-axes sharding for stacked peer-major leaves (T, N, ...): trials
    over the "trials" axis (and the "dcn" axis on a 3-level grid), peer
    rows over each group's "peers" submesh — peer-axis collectives stay
    inside one ICI block by construction."""
    if DCN_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P((DCN_AXIS, TRIAL_AXIS), "peers"))
    return NamedSharding(mesh, P(TRIAL_AXIS, "peers"))


def peer_submesh_sharding(mesh: Mesh) -> NamedSharding:
    """Peer-row sharding of a trial-invariant (N, ...) array on the 2-D
    grid: rows split over the "peers" axis, replicated across trial groups
    (the epoch graph arrays every trial shares)."""
    return NamedSharding(mesh, P("peers"))


def peers_per_group(mesh: Mesh) -> int:
    """Width of the peer submesh inside each trial group (1 on the
    degenerate trials-only grid)."""
    return int(mesh.shape.get("peers", 1))


def nested_batch_shardings(tree, mesh: Mesh, n_rows: int):
    """Sharding pytree for a stacked trial batch (or its eval_shape avals)
    on the nested grid. Rule, by leaf shape: axis 1 == the peer row count
    -> P("trials", "peers") (peer-major state, attacker masks, per-trial
    graph copies); everything else with a leading trial axis -> P("trials")
    (the per-trial scalar clock, PRNG keys, per-round observables). The
    rule is a layout choice, not a semantics choice — GSPMD computes the
    same values under any of these placements."""
    nested = nested_sharding(mesh)
    rows = trial_sharding(mesh)

    def rule(x):
        if getattr(x, "ndim", 0) >= 2 and x.shape[1] == n_rows:
            return nested
        return rows

    return jax.tree_util.tree_map(rule, tree)


def place_trial_batch(stacked, shared: dict, mesh: Mesh,
                      n_rows: int | None = None):
    """Place one stacked trial batch for the sharded campaign window.

    With `n_rows` (the peer row count) the placement is NESTED: stacked
    peer-major leaves shard over both grid axes per nested_batch_shardings
    and the `shared` dict (epoch graph arrays, identical for every trial)
    row-shards over each group's peer submesh. Without it — the legacy
    trial-only layout — stacked leaves shard over "trials" alone and the
    shared arrays replicate. Returns (stacked, shared)."""
    if n_rows is None:
        rows = trial_sharding(mesh)
        rep = replicated(mesh)
        stacked = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rows), stacked)
        shared = {k: jax.device_put(v, rep) for k, v in shared.items()}
        return stacked, shared
    shardings = nested_batch_shardings(stacked, mesh, n_rows)
    stacked = jax.tree_util.tree_map(jax.device_put, stacked, shardings)
    prow = peer_submesh_sharding(mesh)
    shared = {k: jax.device_put(v, prow) for k, v in shared.items()}
    return stacked, shared


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def reshard_rows(x, mesh: Mesh):
    """Place one (N, ...) leaf row-sharded (host-side state swaps like
    set_subscribed / the multi-topic uplink fold keep leaves aligned with
    the rest of the pytree through this)."""
    return jax.device_put(x, peer_sharding(mesh))


def place_simulation(state, arrays: dict, stage, lat, bw, loss, mesh: Mesh):
    """Constructor-side placement shared by the single- and multi-topic
    simulators: row-axis divisibility check, then shard state/graph/topology
    (rows sharded, the tiny stage matrices replicated). Returns
    (state, arrays, stage, lat, bw, loss)."""
    n_rows = state.mesh_mask.shape[0]
    if n_rows % mesh.devices.size != 0:
        raise ValueError(
            f"peer rows {n_rows} must divide evenly over "
            f"{mesh.devices.size} devices"
        )
    topo = {"stage": stage, "lat": lat, "bw": bw}
    if loss is not None:
        topo["loss"] = loss
    state, arrays, topo = shard_simulation(state, arrays, topo, mesh)
    return (state, arrays, topo["stage"], topo["lat"], topo["bw"],
            topo.get("loss"))


def shard_simulation(state, arrays: dict, topo: dict, mesh: Mesh):
    """Place SimState + graph/topology arrays: peer-major rows sharded,
    scalars/clock/key and the tiny stage matrices replicated."""
    rows = peer_sharding(mesh)
    rep = replicated(mesh)

    def place_state(path, x):
        x = jax.numpy.asarray(x)
        if x.ndim >= 1 and x.shape[0] == state.mesh_mask.shape[0]:
            return jax.device_put(x, rows)
        return jax.device_put(x, rep)

    state = jax.tree_util.tree_map_with_path(place_state, state)
    arrays = {k: jax.device_put(v, rows) for k, v in arrays.items()}
    topo_placed = {}
    for k, v in topo.items():
        sh = rows if (v.ndim >= 1 and v.shape[0] == state.mesh_mask.shape[0]) else rep
        topo_placed[k] = jax.device_put(v, sh)
    return state, arrays, topo_placed


# Fixed lane width for every dcn_allreduce payload. Uniform message sizes
# are load-bearing, not cosmetic: the campaign issues back-to-back reduces
# of different logical widths (fence 1, aggregates 2, wall 1), and on an
# oversubscribed host one rank can enter reduce N+1 while its peer still
# drains reduce N — gloo buffers the early bytes as "unexpected" messages,
# which only works when the posted recv is at least as large as the inbound
# preamble (op.preamble.length <= op.nbytes fails otherwise, killing the
# process group). Padding every call to one width removes the mismatched-
# size class entirely; _dcn_reducer reuse below removes the per-call
# re-jit so all reduces of one op share a single executable/communicator.
_DCN_LANES = 4

_dcn_reducers: dict = {}


def _dcn_reducer(op: str, mesh: Mesh, width: int):
    """One cached jitted reduction per (op, device clique, width)."""
    import jax.numpy as jnp

    key = (op, tuple(d.id for d in mesh.devices.flat), width)
    fn = _dcn_reducers.get(key)
    if fn is None:
        body = (lambda a: jnp.sum(a, axis=0)) if op == "sum" \
            else (lambda a: jnp.max(a, axis=0))
        fn = jax.jit(body, out_shardings=NamedSharding(mesh, P()))
        _dcn_reducers[key] = fn
    return fn


def dcn_allreduce(vec, op: str = "sum") -> np.ndarray:
    """All-reduce a small per-process host vector across every process.

    The campaign's cross-process channel for the few global aggregates
    (trial counts, retry totals, wall max) — everything else merges through
    per-rank artifact files. Each process contributes its vector on its
    first addressable device (identity elements elsewhere); one jitted
    reduction over a 1-D all-devices mesh turns into a single DCN
    all-reduce, and because every process must reach it before any can
    leave, the call doubles as the barrier the rank-file merge needs.
    Payloads are padded to _DCN_LANES-float lanes (see above). Returns the
    reduced vector as float32 numpy; `op` is "sum" or "max"."""
    if op not in ("sum", "max"):
        raise ValueError(f"op must be 'sum' or 'max', got {op!r}")
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    vec = np.asarray(vec, np.float32).reshape(-1)
    size = vec.size
    width = max(_DCN_LANES, -(-size // _DCN_LANES) * _DCN_LANES)
    # identity element per op so the padding lanes never perturb the result
    fill = np.float32(0.0 if op == "sum" else -np.inf)
    padded = np.full(width, fill, np.float32)
    padded[:size] = vec
    idle = np.full_like(padded, fill)
    mesh = Mesh(np.array(devs), ("all",))
    sh = NamedSharding(mesh, P("all"))
    first = jax.local_devices()[0]
    shards = [
        jax.device_put((padded if d == first else idle)[None, :], d)
        for d in jax.local_devices()
    ]
    arr = jax.make_array_from_single_device_arrays(
        (len(devs), width), sh, shards)
    reduced = _dcn_reducer(op, mesh, width)(arr)
    return np.asarray(reduced)[:size]
