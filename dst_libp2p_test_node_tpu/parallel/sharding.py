"""Peer-axis sharding: the framework's scale-out story.

The reference scales by spawning more OS processes (one per peer) across
Shadow workers or K8s nodes; its cross-peer traffic rides TCP/QUIC sockets
(SURVEY.md §2 parallelism table). Here the peer axis IS the parallel axis:
every (N, ...) state array shards across TPU chips over a 1-D
`jax.sharding.Mesh` ("peers"), cross-shard mesh edges become XLA collectives
over ICI (gathers through the neighbor index arrays), and multi-host scales
the same mesh over DCN. This is the context-parallel analog the north star
asks for: the 1M-peer adjacency node-sharded across a v5e-8.

Latency/stage constants stay replicated (they are (S+1)^2-tiny); per-peer
rows shard on axis 0. XLA inserts the all-gathers for neighbor lookups; the
explicit shard_map + all_to_all bucketing lives in parallel/exchange.py for
the hand-tuned path.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map graduated from jax.experimental to the jax namespace in 0.6;
# resolve whichever this environment ships so the sharded paths run on both
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent import path
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _exp_shard_map

    # the 0.4.x experimental shard_map has no replication rule for
    # while_loop (the dissemination fixpoint carries one): disable the rep
    # check — out_specs still declare what is replicated, and the psums
    # inside the mapped bodies are what actually replicate it
    shard_map = _partial(_exp_shard_map, check_rep=False)


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host JAX run (DCN scale-out; SURVEY.md §2 'multi-pod via
    DCN'). Wraps jax.distributed.initialize: afterwards jax.devices() spans
    every host's chips and make_peer_mesh() builds the global peer mesh —
    per-iteration fixpoint collectives ride ICI within a slice and DCN
    across hosts, with no change to any engine code. Arguments default to
    the standard JAX env vars (JAX_COORDINATOR_ADDRESS etc.) / TPU metadata.
    Returns the process index."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index()


def make_peer_mesh(n_devices: int | None = None, platform: str | None = None) -> Mesh:
    """1-D peer mesh over the default backend's devices, or over a specific
    platform's (e.g. "cpu" to get the XLA_FLAGS-forced virtual host devices
    even when an accelerator plugin owns the default backend)."""
    devs = jax.devices(platform)
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("peers",))


def peer_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of any (N, ...) peer-major array shard across the mesh."""
    return NamedSharding(mesh, P("peers"))


TRIAL_AXIS = "trials"


def make_trial_mesh(trial_groups: int | None = None,
                    n_devices: int | None = None,
                    platform: str | None = None) -> Mesh:
    """2-D trial x peer device grid for Monte-Carlo campaigns
    (runtime/campaign.py): axis 0 ("trials") partitions the (fraction, seed)
    sweep into independent device groups, axis 1 ("peers") is each group's
    peer-axis subset. Trials are embarrassingly parallel, so the default is
    one device per group (trial_groups = all visible devices) — with >1
    peers per group the window body, whose specs name only "trials",
    REPLICATES over the group's peer devices (the 0.4.x shard_map cannot
    re-shard an inner axis from inside the mapped body), which is correct
    but buys no extra speed."""
    devs = jax.devices(platform)
    if n_devices is not None:
        devs = devs[:n_devices]
    if trial_groups is None:
        trial_groups = len(devs)
    if trial_groups < 1 or len(devs) % trial_groups != 0:
        raise ValueError(
            f"trial_groups {trial_groups} must divide the device count "
            f"{len(devs)} evenly")
    per_group = len(devs) // trial_groups
    grid = np.array(devs).reshape(trial_groups, per_group)
    return Mesh(grid, (TRIAL_AXIS, "peers"))


def trial_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis (stacked-trial) sharding over a make_trial_mesh grid."""
    return NamedSharding(mesh, P(TRIAL_AXIS))


def place_trial_batch(stacked, shared: dict, mesh: Mesh):
    """Place one stacked trial batch for the sharded campaign window:
    every leaf of `stacked` (leading axis = trials) shards over the
    "trials" axis; the `shared` dict (epoch graph arrays, identical for
    every trial) replicates. Returns (stacked, shared)."""
    rows = trial_sharding(mesh)
    rep = replicated(mesh)
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, rows), stacked)
    shared = {k: jax.device_put(v, rep) for k, v in shared.items()}
    return stacked, shared


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def reshard_rows(x, mesh: Mesh):
    """Place one (N, ...) leaf row-sharded (host-side state swaps like
    set_subscribed / the multi-topic uplink fold keep leaves aligned with
    the rest of the pytree through this)."""
    return jax.device_put(x, peer_sharding(mesh))


def place_simulation(state, arrays: dict, stage, lat, bw, loss, mesh: Mesh):
    """Constructor-side placement shared by the single- and multi-topic
    simulators: row-axis divisibility check, then shard state/graph/topology
    (rows sharded, the tiny stage matrices replicated). Returns
    (state, arrays, stage, lat, bw, loss)."""
    n_rows = state.mesh_mask.shape[0]
    if n_rows % mesh.devices.size != 0:
        raise ValueError(
            f"peer rows {n_rows} must divide evenly over "
            f"{mesh.devices.size} devices"
        )
    topo = {"stage": stage, "lat": lat, "bw": bw}
    if loss is not None:
        topo["loss"] = loss
    state, arrays, topo = shard_simulation(state, arrays, topo, mesh)
    return (state, arrays, topo["stage"], topo["lat"], topo["bw"],
            topo.get("loss"))


def shard_simulation(state, arrays: dict, topo: dict, mesh: Mesh):
    """Place SimState + graph/topology arrays: peer-major rows sharded,
    scalars/clock/key and the tiny stage matrices replicated."""
    rows = peer_sharding(mesh)
    rep = replicated(mesh)

    def place_state(path, x):
        x = jax.numpy.asarray(x)
        if x.ndim >= 1 and x.shape[0] == state.mesh_mask.shape[0]:
            return jax.device_put(x, rows)
        return jax.device_put(x, rep)

    state = jax.tree_util.tree_map_with_path(place_state, state)
    arrays = {k: jax.device_put(v, rows) for k, v in arrays.items()}
    topo_placed = {}
    for k, v in topo.items():
        sh = rows if (v.ndim >= 1 and v.shape[0] == state.mesh_mask.shape[0]) else rep
        topo_placed[k] = jax.device_put(v, sh)
    return state, arrays, topo_placed
