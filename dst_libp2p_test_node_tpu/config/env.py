"""Node configuration layer (reference L2): env-var parsing, peer identity, ports.

Mirrors the env surface of every reference node:
  - flagship GossipSub node: nim-test-node/gossipsub-queues/env.nim:5-36 and
    the ~20 GOSSIPSUB_* overrides in main.nim:252-306;
  - go node: go-test-node/env.go:21-105; rust node: rust-test-node/src/env.rs:10-87;
  - role-based nodes (NODE_ROLE): connmanager/env.nim:7-105, kad-dht/env.nim:8-35,
    service-discovery/env.nim:6-189; regression/env.nim:5-37.

Deliberate quirk resolutions (SURVEY.md §7 "known reference quirks"):
  - SHADOWENV: topogen writes "1" but Nim/Go/Rust test == "true"
    (topogen.py:7,110 vs env.nim:6/env.go:28/env.rs:55-57). We accept
    1|true|yes|on, as service-discovery's parser already does (env.nim:66-74).
  - identity: hostname-ordinal. Nim takes the LAST '-'-separated field
    (env.nim:16), Go/Rust take field [1] (env.go:67, env.rs:34). We follow Nim
    (last field) — correct for StatefulSet names like "nimp2p-0" AND "pod-12".
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field

# Fixed port contract (SURVEY.md Appendix B).
LIBP2P_PORT = 5000       # env.nim:9 (overridable via PORT in role-based nodes)
PROMETHEUS_PORT = 8008   # env.nim:8, env.go:23, env.rs:12
HTTP_CONTROL_PORT = 8645  # env.nim:7, env.go:24, env.rs:11

_TRUTHY = {"1", "true", "yes", "on"}


def env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if v == "":
        return default
    return v.strip().lower() in _TRUTHY


def env_int(name: str, default: int) -> int:
    """Invalid values fall back to the default with no exception, matching the
    reference's getEnvInt (gossipsub-queues/main.nim:79-91)."""
    v = os.environ.get(name, "")
    if v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    if v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def env_str(name: str, default: str) -> str:
    v = os.environ.get(name, "")
    return v if v != "" else default


def hostname_ordinal(hostname: str | None = None) -> int:
    """'pod-12' -> 12, 'nimp2p-service-3' -> 3 (env.nim:16: split('-')[^1]).

    An unparseable hostname falls back to ordinal 0. The reference is split on
    this: the flagship node's bare parseInt raises (env.nim:16) while
    connmanager deliberately catches and defaults to 0
    (connmanager/env.nim:93-95); we follow the forgiving rule so the framework
    also runs outside ordinal-named StatefulSet pods (tests, notebooks)."""
    h = hostname if hostname is not None else socket.gethostname()
    try:
        return int(h.split("-")[-1])
    except ValueError:
        return 0


@dataclass
class GossipSubParams:
    """GossipSub tunables with the reference's defaults.

    Sources: gossipsub-queues/main.nim:252-306 (env names + defaults),
    go-test-node/main.go:153-174, rust-test-node/src/main.rs:223-241.
    """

    d: int = 6
    d_low: int = 4
    d_high: int = 8
    d_score: int | None = None  # default = dLow (main.nim:257)
    d_out: int | None = None    # default = d div 2 (main.nim:258)
    d_lazy: int | None = None   # default = d (main.nim:259)

    heartbeat_ms: int = 1000
    prune_backoff_sec: int = 60

    max_high_priority_queue_len: int = 256
    max_medium_priority_queue_len: int = 512
    max_low_priority_queue_len: int = 1024

    slow_peer_penalty_weight: float = 0.0
    slow_peer_penalty_threshold: float = 2.0
    slow_peer_penalty_decay: float = 0.2

    decay_interval_ms: int = 1000
    decay_to_zero: float = 0.01

    flood_publish: bool = True
    opportunistic_graft_threshold: float = -10000.0
    gossip_factor: float = 0.25
    # score thresholds: the reference parses these but comments the
    # assignments out (main.nim:276-278,306-308), so nim-libp2p's defaults
    # apply — these values. The env names match the commented-out surface.
    gossip_threshold: float = -100.0
    publish_threshold: float = -1000.0
    graylist_threshold: float = -10000.0
    # mcache gossip window: IHAVE re-samples targets every heartbeat for this
    # many rounds after a message enters the cache (nim-libp2p
    # GossipSubHistoryGossip default; gossip every heartbeat over history,
    # main.nim:259,283)
    history_gossip: int = 3

    # topicParams (main.nim:335-340)
    topic_weight: float = 1.0
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_cap: float = 30.0
    first_message_deliveries_decay: float = 0.9

    # go node extension: IDONTWANT threshold (go-test-node/main.go:165)
    idontwant_message_threshold: int = 1000

    def __post_init__(self) -> None:
        # derived defaults follow their base params however the object is
        # built (env path and direct construction share one rule)
        if self.d_score is None:
            self.d_score = self.d_low
        if self.d_out is None:
            self.d_out = self.d // 2
        if self.d_lazy is None:
            self.d_lazy = self.d

    def validate(self) -> None:
        if not (self.d_low <= self.d <= self.d_high):
            raise ValueError(
                f"require D_low <= D <= D_high, got {self.d_low} <= {self.d} <= {self.d_high}"
            )
        if self.heartbeat_ms <= 0:
            raise ValueError("heartbeat_ms must be positive")
        if self.history_gossip < 1:
            raise ValueError(
                f"history_gossip must be >= 1, got {self.history_gossip}")
        for name in ("gossip_threshold", "publish_threshold",
                     "graylist_threshold"):
            if getattr(self, name) > 0:
                raise ValueError(f"{name} must be <= 0 (v1.1 spec)")


def gossipsub_params_from_env() -> GossipSubParams:
    d = env_int("GOSSIPSUB_D", 6)
    d_low = env_int("GOSSIPSUB_D_LOW", 4)
    p = GossipSubParams(
        d=d,
        d_low=d_low,
        d_high=env_int("GOSSIPSUB_D_HIGH", 8),
        d_score=env_int("GOSSIPSUB_D_SCORE", d_low),
        d_out=env_int("GOSSIPSUB_D_OUT", d // 2),
        d_lazy=env_int("GOSSIPSUB_D_LAZY", d),
        heartbeat_ms=env_int("GOSSIPSUB_HEARTBEAT_MS", 1000),
        prune_backoff_sec=env_int("GOSSIPSUB_PRUNE_BACKOFF_SEC", 60),
        max_high_priority_queue_len=env_int("GOSSIPSUB_MAX_HIGH_PRIORITY_QUEUE_LEN", 256),
        max_medium_priority_queue_len=env_int("GOSSIPSUB_MAX_MEDIUM_PRIORITY_QUEUE_LEN", 512),
        max_low_priority_queue_len=env_int("GOSSIPSUB_MAX_LOW_PRIORITY_QUEUE_LEN", 1024),
        slow_peer_penalty_weight=env_float("GOSSIPSUB_SLOW_PEER_PENALTY_WEIGHT", 0.0),
        slow_peer_penalty_threshold=env_float("GOSSIPSUB_SLOW_PEER_PENALTY_THRESHOLD", 2.0),
        slow_peer_penalty_decay=env_float("GOSSIPSUB_SLOW_PEER_PENALTY_DECAY", 0.2),
        decay_interval_ms=env_int("GOSSIPSUB_DECAY_INTERVAL_MS", 1000),
        decay_to_zero=env_float("GOSSIPSUB_DECAY_TO_ZERO", 0.01),
        flood_publish=env_bool("GOSSIPSUB_FLOOD_PUBLISH", True),
        opportunistic_graft_threshold=env_float("GOSSIPSUB_OPPORTUNISTIC_GRAFT_THRESHOLD", -10000.0),
        gossip_factor=env_float("GOSSIPSUB_GOSSIP_FACTOR", 0.25),
        gossip_threshold=env_float("GOSSIPSUB_GOSSIP_THRESHOLD", -100.0),
        publish_threshold=env_float("GOSSIPSUB_PUBLISH_THRESHOLD", -1000.0),
        graylist_threshold=env_float("GOSSIPSUB_GRAYLIST_THRESHOLD", -10000.0),
        history_gossip=env_int("GOSSIPSUB_HISTORY_GOSSIP", 3),
        idontwant_message_threshold=env_int("GOSSIPSUB_IDONTWANT_THRESHOLD", 1000),
    )
    p.validate()
    return p


VALID_MUXERS = ("yamux", "mplex", "quic")


@dataclass
class NodeConfig:
    """The shared node surface (getPeerDetails: env.nim:13-36, env.go:21-105)."""

    my_id: int = 0
    network_size: int = 100
    connect_to: int = 10
    muxer: str = "yamux"
    fragments: int = 1
    in_shadow: bool = False
    max_connections: int = 250       # main.nim:429
    self_trigger: bool = True        # SELFTRIGGER (main.nim:245)
    peer_id_offset: int = 0          # env.nim:17
    service: str = "nimp2p-service"  # main.nim:383
    file_path: str = "./"            # env.nim:22 (parsed but unused in reference)
    publishers: int = 10             # topogen env PUBLISHERS (topogen.py:111)
    topic: str = "test"              # main.nim:450
    role: str = ""                   # NODE_ROLE for role-based nodes

    # Mix-routing surface documented in the root README (README.md:30,42-46)
    # but absent from the reference snapshot's code — implemented here per
    # SURVEY.md §5 (BASELINE config 5 requires it).
    mounts_mix: bool = False
    uses_mix: bool = False
    num_mix: int = 0
    mix_d: int = 4

    gossipsub: GossipSubParams = field(default_factory=GossipSubParams)

    def validate(self) -> None:
        if self.muxer.lower() not in VALID_MUXERS:
            raise ValueError(f"Unknown muxer type : {self.muxer}")
        if self.connect_to >= self.network_size:
            raise ValueError(
                "Not enough peers to make target connections. Network size : "
                f"{self.network_size}"
            )
        if self.uses_mix and self.num_mix < self.mix_d + 1:
            # fail fast on the surface BASELINE config 5 depends on, rather
            # than silently running without anonymity. The +1: any peer may
            # publish via /publish, and a mix-node publisher is excluded
            # from its own relay path
            raise ValueError(
                f"USESMIX requires NUMMIX >= MIXD + 1, got "
                f"NUMMIX={self.num_mix} MIXD={self.mix_d}"
            )
        self.gossipsub.validate()

    @property
    def address(self) -> str:
        """Listen multiaddr (env.nim:23-26)."""
        if self.muxer.lower() == "quic":
            return f"/ip4/0.0.0.0/udp/{LIBP2P_PORT}/quic-v1"
        return f"/ip4/0.0.0.0/tcp/{LIBP2P_PORT}"


def get_peer_details(hostname: str | None = None) -> NodeConfig:
    """Parse the canonical env surface into a NodeConfig (env.nim:13-36)."""
    in_shadow = env_bool("SHADOWENV", False)
    cfg = NodeConfig(
        my_id=env_int("PEER_ID_OFFSET", 0) + hostname_ordinal(hostname),
        network_size=env_int("PEERS", 100),
        connect_to=env_int("CONNECTTO", 10),
        muxer=env_str("MUXER", "yamux"),
        fragments=env_int("FRAGMENTS", 1),
        in_shadow=in_shadow,
        max_connections=env_int("MAXCONNECTIONS", 250),
        self_trigger=env_bool("SELFTRIGGER", True),
        peer_id_offset=env_int("PEER_ID_OFFSET", 0),
        service=env_str("SERVICE", "nimp2p-service"),
        file_path="../" if in_shadow else env_str("FILEPATH", "./"),
        publishers=env_int("PUBLISHERS", 10),
        role=env_str("NODE_ROLE", ""),
        mounts_mix=env_bool("MOUNTSMIX", False),
        uses_mix=env_bool("USESMIX", False),
        num_mix=env_int("NUMMIX", 0),
        mix_d=env_int("MIXD", 4),
        gossipsub=gossipsub_params_from_env(),
    )
    cfg.validate()
    return cfg
