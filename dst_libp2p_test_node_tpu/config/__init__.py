from .env import (
    NodeConfig,
    GossipSubParams,
    env_bool,
    env_int,
    env_float,
    get_peer_details,
    gossipsub_params_from_env,
)
from .topology import Topology, TopoParams

__all__ = [
    "NodeConfig",
    "GossipSubParams",
    "env_bool",
    "env_int",
    "env_float",
    "get_peer_details",
    "gossipsub_params_from_env",
    "Topology",
    "TopoParams",
]
