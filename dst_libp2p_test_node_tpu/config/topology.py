"""Topology substrate: the reference topogen contract as dense stage matrices.

The reference (shadow/topogen.py) builds a complete networkx graph over
`anchor_stages` *network nodes* (not peers): stage s gets host bandwidth
`ceil(s*bw_jump + min_bw)` Mbit (bw_jump = int((max_bw-min_bw)/stages)), the
edge between stages i<j gets latency `min(ceil((stages-j)*lat_jump + min_lat),
max_lat)` ms, each stage's self-loop gets `max((stages-i)*lat_jump, min_lat)`
ms, and an extra "fast node" (stage index = stages) for the message injector
gets 100 Mbit and 1 ms edges (topogen.py:39-71). Peers pod-0..pod-(n-1) are
assigned round-robin to stages: peer p -> stage p % stages (topogen.py:121-122).

TPU-first consequence: per-edge link properties collapse to a tiny
(stages+1)x(stages+1) latency matrix plus per-stage bandwidth vectors, and a
length-N int8/int32 stage vector — peer-pair latency is `LAT[stage[p],
stage[q]]`, a 2-gather, no N x N materialization at any scale.

We both *emit* network_topology.gml + shadow.yaml (same schema, so existing
Shadow tooling can consume our configs) and *ingest* a GML produced by the
reference topogen (so `SIMBACKEND=tpu` can run an existing experiment dir).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

import numpy as np

GML_FILE = "network_topology.gml"
YAML_FILE = "shadow.yaml"

# Fixed by the reference for every generated experiment (topogen.py:7-8).
SHADOW_ENV_FLAG = 1
CONNECTIONS = 10


@dataclass(frozen=True)
class TopoParams:
    """CLI surface of topogen.py:13-36 (flag names in comments)."""

    network_size: int = 100      # -n/--network-size
    min_bandwidth: int = 50      # -bl, Mbps
    max_bandwidth: int = 50      # -bh, Mbps
    min_latency: int = 100       # -ll, ms
    max_latency: int = 100       # -lh, ms
    anchor_stages: int = 1       # -st
    packet_loss: float = 0.0     # -l, rate 0-1
    msg_size_bytes: int = 1500   # -s
    num_frags: int = 1           # -f, choices 1..9
    messages: int = 10           # -m (a.k.a. num_publishers in shadow.yaml env)
    delay_seconds: float = 0.1   # -d, inter-message delay
    muxer: str = "yamux"         # -mx, choices mplex|yamux|quic

    def validate(self) -> None:
        if self.min_bandwidth > self.max_bandwidth:
            raise ValueError("min_bandwidth cannot exceed max_bandwidth")
        if self.min_latency > self.max_latency:
            raise ValueError("min_latency cannot exceed max_latency")
        if not (1 <= self.num_frags <= 9):
            raise ValueError("num_frags must be in 1..9")
        if self.muxer not in ("mplex", "yamux", "quic"):
            raise ValueError(f"invalid muxer {self.muxer}")
        if self.anchor_stages < 1:
            raise ValueError("anchor_stages must be >= 1")


def _stage_bandwidth_mbit(s: int, p: TopoParams) -> int:
    jump = int((p.max_bandwidth - p.min_bandwidth) / p.anchor_stages)
    return math.ceil(s * jump + p.min_bandwidth)


def _edge_latency_ms(i: int, j: int, p: TopoParams) -> int:
    """Latency of the (unordered) stage pair; i == j is the self-loop rule."""
    jump = int((p.max_latency - p.min_latency) / p.anchor_stages)
    lo, hi = min(i, j), max(i, j)
    if lo == hi:
        return max((p.anchor_stages - lo) * jump, p.min_latency)
    return min(math.ceil((p.anchor_stages - hi) * jump + p.min_latency), p.max_latency)


@dataclass
class Topology:
    """Dense-matrix form of a staged experiment topology.

    latency_ms:    (S+1, S+1) float32 — symmetric stage-pair latency; row/col S
                   is the injector's fast node (1 ms everywhere).
    bw_up_mbit:    (S+1,) float32 per-stage host uplink (== downlink).
    packet_loss:   (S+1, S+1) float32 per stage pair.
    stage_of_peer: (N,) int32 — peer p sits on network node p % S.
    """

    params: TopoParams
    latency_ms: np.ndarray
    bw_up_mbit: np.ndarray
    packet_loss: np.ndarray
    stage_of_peer: np.ndarray

    @property
    def n_peers(self) -> int:
        return int(self.stage_of_peer.shape[0])

    @property
    def n_stages(self) -> int:
        return int(self.bw_up_mbit.shape[0]) - 1

    @property
    def injector_stage(self) -> int:
        return self.n_stages

    def tx_ms_per_peer(self, payload_bytes: int) -> np.ndarray:
        """Serialization (transmit) time of one payload on each peer's uplink,
        in ms: bytes*8 / (Mbit/s * 1e6) * 1e3."""
        bw = self.bw_up_mbit[self.stage_of_peer]  # (N,)
        return (payload_bytes * 8.0) / (bw * 1e6) * 1e3

    def peer_latency_ms(self, p: int, q: int) -> float:
        return float(self.latency_ms[self.stage_of_peer[p], self.stage_of_peer[q]])

    # ------------------------------------------------------------------ build

    @classmethod
    def build(cls, params: TopoParams) -> "Topology":
        params.validate()
        s = params.anchor_stages
        lat = np.ones((s + 1, s + 1), dtype=np.float32)  # injector row/col = 1 ms
        loss = np.zeros((s + 1, s + 1), dtype=np.float32)
        bw = np.empty(s + 1, dtype=np.float32)
        for i in range(s):
            bw[i] = _stage_bandwidth_mbit(i, params)
            for j in range(i, s):
                lat[i, j] = lat[j, i] = _edge_latency_ms(i, j, params)
                loss[i, j] = loss[j, i] = params.packet_loss
        bw[s] = 100.0  # injector fast node: 100 Mbit, 1 ms (topogen.py:65-69)
        stage = (np.arange(params.network_size) % s).astype(np.int32)
        return cls(params, lat, bw, loss, stage)

    # ------------------------------------------------------------------- emit

    def write_gml(self, path: str = GML_FILE) -> None:
        import networkx as nx

        s = self.n_stages
        g = nx.complete_graph(s)
        for i in range(s):
            bw_str = f"{int(self.bw_up_mbit[i])} Mbit"
            g.nodes[i]["host_bandwidth_up"] = bw_str
            g.nodes[i]["host_bandwidth_down"] = bw_str
            g.add_edge(i, i)
            for j in range(i, s):
                g.edges[i, j]["latency"] = f"{int(self.latency_ms[i, j])} ms"
                g.edges[i, j]["packet_loss"] = float(self.packet_loss[i, j])
        g.add_node(s, host_bandwidth_up="100 Mbit", host_bandwidth_down="100 Mbit")
        for i in range(s + 1):
            g.add_edge(i, s, latency="1 ms", packet_loss=0.0)
        nx.write_gml(g, path)

    def shadow_config(self) -> dict:
        """shadow.yaml dict in the reference schema (topogen.py:74-136)."""
        p = self.params
        node_env = {
            "PEERS": str(p.network_size),
            "SHADOWENV": str(SHADOW_ENV_FLAG),
            "CONNECTTO": str(CONNECTIONS),
            "PUBLISHERS": str(p.messages),
            "FRAGMENTS": str(p.num_frags),
            "MUXER": p.muxer,
        }
        hosts: dict = {}
        stage_host = {}
        for i in range(self.n_stages):
            stage_host[i] = {
                "network_node_id": i,
                "processes": [
                    {"path": "./main", "start_time": "5s", "environment": dict(node_env)}
                ],
            }
        for i in range(p.network_size):
            hosts[f"pod-{i}"] = stage_host[i % self.n_stages]
        controller_args = (
            f"../../../traffic_sync.py -s {p.msg_size_bytes} -m {p.messages} "
            f"-d {p.delay_seconds} -n {p.network_size} --peer-selection id"
        )
        hosts[f"pod-{p.network_size}"] = {
            "network_node_id": self.injector_stage,
            "processes": [
                {
                    "path": "/usr/bin/python",
                    "args": controller_args,
                    "start_time": "500s",
                    "environment": {"SHADOWENV": str(SHADOW_ENV_FLAG)},
                }
            ],
        }
        return {
            "general": {
                "bootstrap_end_time": "10s",
                "heartbeat_interval": "12s",
                "stop_time": "15m",
                "progress": True,
            },
            "experimental": {"use_memory_manager": False},
            "network": {"graph": {"type": "gml", "file": {"path": GML_FILE}}},
            "hosts": hosts,
        }

    def write_shadow_yaml(self, path: str = YAML_FILE) -> None:
        import yaml

        with open(path, "w") as f:
            yaml.dump(self.shadow_config(), f, default_flow_style=False, sort_keys=False)

    # ----------------------------------------------------------------- ingest

    @classmethod
    def from_gml(cls, path: str, network_size: int, params: TopoParams | None = None) -> "Topology":
        """Load a topology emitted by the reference topogen (or by us)."""
        import networkx as nx

        g = nx.read_gml(path, label="id")
        n_nodes = g.number_of_nodes()
        s = n_nodes - 1  # last node is the injector fast node
        lat = np.ones((n_nodes, n_nodes), dtype=np.float32)
        loss = np.zeros((n_nodes, n_nodes), dtype=np.float32)
        bw = np.full(n_nodes, 100.0, dtype=np.float32)
        for i, data in g.nodes(data=True):
            b = data.get("host_bandwidth_up", "100 Mbit")
            bw[i] = float(str(b).split()[0])
        for i, j, data in g.edges(data=True):
            l_ms = float(str(data.get("latency", "1 ms")).split()[0])
            lat[i, j] = lat[j, i] = l_ms
            pl = float(data.get("packet_loss", 0.0))
            loss[i, j] = loss[j, i] = pl
        stage = (np.arange(network_size) % s).astype(np.int32)
        if params is None:
            params = TopoParams(network_size=network_size, anchor_stages=s)
        else:
            params = replace(params, network_size=network_size, anchor_stages=s)
        return cls(params, lat, bw, loss, stage)
